"""Self-healing serving tests (serving/faults.py + round-9 recovery wiring).

Layout mirrors the round-9 issue:

* unit lane — taxonomy, seeded-schedule determinism, injector counting,
  breaker state machine on a fake clock (no device, no sleeps);
* baseline lane — the *pre-existing* terminal failure paths, pinned before
  the retry layer is trusted: a permanent batch failure is confined to its
  own group, resident ``fail()`` drains queued AND attached jobs, and
  ``_drain_on_stop`` resolves every pending event (no hung ``Job.wait``);
* recovery lane — the acceptance criteria end to end, driven entirely by
  injected schedules: a seeded schedule faulting >=10% of dispatches on the
  static, resident, and bulk paths completes every job bit-identical to a
  fault-free run with zero terminal errors; a poison job is bisected out
  and fails alone; breaker open -> half-open -> closed transitions are
  asserted deterministically on an injected clock (no wall-clock sleeps
  drive any transition — `wait_for` below only *observes*).

Engine shapes reuse test_engine/test_scheduler's SMALL / FUSED_SMALL / RC
so the compiled programs are shared across modules; the one compile-heavy
first device test requests ``heavy_compile_guard`` (ONCE per module — see
test_scheduler.py's module note on why per-test guards regress the suite).
"""

import threading
import time

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine
from distributed_sudoku_solver_tpu.serving.scheduler import (
    ResidentConfig,
    ResidentFlight,
)
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
FUSED_SMALL = SolverConfig(
    min_lanes=8, stack_slots=16, step_impl="fused", fused_steps=2
)
RC = ResidentConfig(
    job_slots=4, gang_lanes=4, queue_depth=32, attach_batch=4, chunk_steps=16
)


def wait_for(pred, timeout=60.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


class FakeClock:
    """Injectable policy clock: transitions advance when the TEST says so."""

    def __init__(self, t0: float = 1000.0):
        self.t = t0
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            return self.t

    def advance(self, dt: float) -> None:
        with self._lock:
            self.t += dt


# -- unit lane: taxonomy / schedules / injector / breaker ---------------------


def test_classification_taxonomy():
    assert faults.classify(faults.SimulatedFault("oom", "s", 0)) == faults.TRANSIENT
    assert faults.classify(faults.SimulatedFault("preempt", "s", 0)) == faults.TRANSIENT
    assert (
        faults.classify(faults.SimulatedFault("permanent", "s", 0))
        == faults.PERMANENT
    )
    assert faults.classify(ValueError("shape mismatch")) == faults.PERMANENT
    assert faults.classify(RuntimeError("device hiccup")) == faults.TRANSIENT
    # Flattened-string judgement (cluster SOLUTION payloads, job.error).
    assert faults.classify_message("ValueError: grid shape") == faults.PERMANENT
    assert faults.classify_message("engine stopped") == faults.TRANSIENT
    assert faults.classify_message(None) == faults.TRANSIENT
    assert (
        faults.classify_message("INVALID_ARGUMENT: poisoned [permanent]")
        == faults.PERMANENT
    )
    assert faults.is_oom(faults.SimulatedFault("oom", "s", 0))
    assert faults.is_oom("RESOURCE_EXHAUSTED: whatever")
    assert not faults.is_oom(RuntimeError("preempted"))


def test_seeded_schedule_deterministic_and_order_independent():
    a = faults.FaultSchedule.seeded(seed=11, rate=0.3)
    b = faults.FaultSchedule.seeded(seed=11, rate=0.3)
    # Same seed -> identical decisions, whatever order sites are queried in.
    fwd = [a.lookup("engine.advance", i) for i in range(200)]
    rev = [b.lookup("engine.advance", i) for i in reversed(range(200))]
    assert fwd == rev[::-1]
    hits = sum(1 for k in fwd if k is not None)
    assert 20 <= hits <= 100, hits  # rate=0.3 over 200 draws
    # Different sites draw independently; a different seed reshuffles.
    assert fwd != [a.lookup("resident.advance", i) for i in range(200)]
    c = faults.FaultSchedule.seeded(seed=12, rate=0.3)
    assert fwd != [c.lookup("engine.advance", i) for i in range(200)]
    with pytest.raises(ValueError):
        faults.FaultSchedule.seeded(seed=1, rate=0.5, kinds=("nope",))


def test_injector_counts_sites_and_poisons_jobs():
    inj = faults.FaultInjector(
        faults.FaultSchedule.at({"x": {1: "preempt"}}), poison_jobs=("bad",)
    )
    inj.fire("x", uuids=("good",))  # index 0: clean
    with pytest.raises(faults.SimulatedFault) as exc:
        inj.fire("x", uuids=("good",))  # index 1: scheduled preempt
    assert exc.value.kind == "preempt" and exc.value.transient
    with pytest.raises(faults.SimulatedFault) as exc:
        inj.fire("y", uuids=("good", "bad"))  # poison follows the job
    assert exc.value.kind == "permanent" and not exc.value.transient
    m = inj.metrics()
    assert m["dispatches"] == {"x": 2, "y": 1}
    assert m["injected"] == {"x:preempt": 1, "y:permanent": 1}
    assert inj.dispatches() == 3
    # No injector installed: the seam is a no-op.
    faults.fire("anywhere", uuids=("bad",))


def test_breaker_state_machine_on_fake_clock():
    clock = FakeClock()
    pol = faults.RecoveryPolicy(
        breaker_failures=3, breaker_cooldown_s=10.0, clock=clock
    )
    br = faults.CircuitBreaker(pol)
    assert br.state == br.CLOSED and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == br.CLOSED and br.allow()  # under threshold
    br.record_failure()  # third consecutive: open
    assert br.state == br.OPEN and not br.allow()
    clock.advance(9.9)
    assert not br.allow()  # cooldown not yet elapsed
    clock.advance(0.2)
    assert br.allow()  # flips to half-open; the caller is the probe
    assert br.state == br.HALF_OPEN
    assert not br.allow()  # single probe: later callers denied until it resolves
    # A probe that dies resolving NEITHER way (cancelled before a chunk)
    # must not wedge half-open forever: one re-grant per cooldown window.
    clock.advance(10.1)
    assert br.allow() and br.state == br.HALF_OPEN
    assert not br.allow()
    br.record_failure()  # probe failed: straight back to open
    assert br.state == br.OPEN and not br.allow()
    clock.advance(10.1)
    assert br.allow() and br.state == br.HALF_OPEN
    br.record_success()  # probe succeeded
    assert br.state == br.CLOSED and br.consecutive_failures == 0
    assert br.metrics()["transitions"] == 5  # open, half, open, half, closed
    # Interleaved successes keep resetting the consecutive count.
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == br.CLOSED


# -- baseline lane: the pre-existing terminal paths ---------------------------


def test_permanent_batch_failure_confined_to_its_group(heavy_compile_guard):
    """A group whose launch fails with a permanent (ValueError-shaped)
    error fails exactly its own jobs — a concurrent other-group job
    completes, and the loop keeps serving (the round-9 baseline of the old
    'batch failed' path)."""
    eng = SolverEngine(
        config=SolverConfig(lanes=2, stack_slots=4), max_batch=8
    ).start()
    try:
        bad_roots = np.ones((2 * (1 + 4) + 1, 9, 9), np.uint32)  # > capacity
        j = eng.submit_roots(bad_roots, SUDOKU_9)
        ok = eng.submit(EASY_9)
        assert j.wait(60)
        assert j.error and not j.solved
        assert "ValueError" in j.error
        assert ok.wait(60) and ok.solved, "other group caught the failure"
        assert eng.metrics()["faults"]["permanent_failures"] == 1
    finally:
        eng.stop(timeout=2)


def test_resident_fail_drains_queued_and_attached():
    """Terminal ``fail()`` (the pre-round-9 semantics, kept as the last
    resort): every held job — attached slots AND admission queue — resolves
    with the error, and admission closes."""
    eng = SolverEngine(config=SMALL, max_batch=8, resident=RC)  # not started
    rf = ResidentFlight(eng, SUDOKU_9, RC)
    attached = Job(uuid="a", grid=np.asarray(EASY_9, np.int32), geom=SUDOKU_9)
    queued = Job(uuid="q", grid=np.asarray(EASY_9, np.int32), geom=SUDOKU_9)
    rf.slots[1] = attached
    rf._pending.append(queued)
    rf.fail(RuntimeError("device exploded"))
    for job in (attached, queued):
        assert job.done.is_set(), "fail() stranded a held job"
        assert job.error and "device exploded" in job.error
    fresh = Job(uuid="f", grid=np.asarray(EASY_9, np.int32), geom=SUDOKU_9)
    assert not rf.try_admit(fresh), "admission still open after terminal fail"
    assert rf.closed_deflected == 1  # the bypass is observable on /metrics
    assert all(s is None for s in rf.slots)


def test_drain_on_stop_resolves_every_pending_event():
    """stop() must resolve queued, in-flight, AND resident-queued jobs —
    an un-set done event would hang any ``Job.wait`` without a timeout."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.1, resident=RC
    ).start()
    warm = eng.submit(EASY_9)
    assert warm.wait(60)
    jobs = [eng.submit(HARD_9[1]) for _ in range(6)]  # slots + queue + static
    jobs.append(eng.submit(HARD_9[0], config=SMALL))  # static path (override)
    eng.stop(timeout=10)
    for j in jobs:
        assert j.wait(5), f"job {j.uuid} stranded by stop()"
        assert j.done.is_set()
        assert j.solved or j.error == "engine stopped"


# -- recovery lane: the acceptance criteria, schedule-driven ------------------


def _solve_all(eng, boards, timeout=180):
    jobs = [eng.submit(b) for b in boards]
    for j in jobs:
        assert j.wait(timeout), (j.error, j.fault_retries, j.last_fault)
    return jobs


def test_static_path_transient_schedule_bit_identical():
    """>=10% of static-path dispatches fault transiently (launch, advance,
    and status-fetch seams): every job completes with zero terminal errors
    and solutions bit-identical to a fault-free run."""
    boards = [np.asarray(p) for p in HARD_9] * 2
    eng = SolverEngine(config=SMALL, max_batch=4).start()
    try:
        baseline = _solve_all(eng, boards)
    finally:
        eng.stop(timeout=2)
    # rate=0.3 (not 0.1) because the assertion below is on the REALIZED
    # ratio: the static path resolves these boards in ~a dozen dispatches,
    # and a thin Bernoulli over so few draws can land under 10%.  The
    # budget is generous on purpose — every flight failure charges EVERY
    # job the flight holds, so a hot schedule compounds per-job retries
    # far past the per-dispatch rate.
    inj = faults.FaultInjector(
        faults.FaultSchedule.seeded(
            seed=41,
            rate=0.3,
            sites=("engine.launch", "engine.advance", "fetch.status"),
        )
    )
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL,
            max_batch=4,
            recovery=faults.RecoveryPolicy(max_retries=25),
        ).start()
        try:
            jobs = _solve_all(eng, boards)
            m = eng.metrics()["faults"]
        finally:
            eng.stop(timeout=2)
    for base, job in zip(baseline, jobs):
        assert job.solved and job.error is None, (job.error, job.last_fault)
        np.testing.assert_array_equal(job.solution, base.solution)
    im = inj.metrics()
    injected = sum(im["injected"].values())
    dispatches = sum(im["dispatches"].values())
    assert injected >= 1 and dispatches >= 1
    assert injected / dispatches >= 0.10, (injected, dispatches)
    assert m["retries"] >= injected  # flight failures charge every holder
    assert m["requeues"] >= 1 and m["budget_exhausted"] == 0


def test_resident_path_transient_schedule_bit_identical():
    """The resident twin: faults on attach/advance/status rebuild the
    flight (jobs requeued, not errored) and every job still completes
    bit-identical to the fault-free resident run."""
    boards = [np.asarray(p) for p in HARD_9] * 2
    eng = SolverEngine(config=SMALL, max_batch=8, resident=RC).start()
    try:
        baseline = _solve_all(eng, boards)
        assert eng.metrics()["resident"]["9x9"]["admitted"] >= len(boards)
    finally:
        eng.stop(timeout=2)
    inj = faults.FaultInjector(
        faults.FaultSchedule.seeded(
            seed=5,
            rate=0.25,
            sites=("resident.attach", "resident.advance", "fetch.status"),
        )
    )
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL,
            max_batch=8,
            resident=RC,
            recovery=faults.RecoveryPolicy(
                max_retries=10, rebuild_cooldown_s=0.0
            ),
        ).start()
        try:
            jobs = _solve_all(eng, boards)
            m = eng.metrics()
        finally:
            eng.stop(timeout=2)
    for base, job in zip(baseline, jobs):
        assert job.solved and job.error is None, (job.error, job.last_fault)
        np.testing.assert_array_equal(job.solution, base.solution)
    im = inj.metrics()
    injected = sum(im["injected"].values())
    assert injected >= 1
    assert injected / sum(im["dispatches"].values()) >= 0.10
    rm = m["resident"]["9x9"]["faults"]
    assert rm["rebuilds"] >= 1 and rm["rebuild_requeued"] >= 1
    assert m["faults"]["budget_exhausted"] == 0


def test_fused_transient_fault_downgrades_to_composite():
    """The degraded-fallback ladder: a fused flight's transient fault
    requeues its jobs on the composite step (observable on /metrics), and
    an OOM halves the retry's lane width."""
    # Dispatch order: launch#0 clean, advance#0 runtime-faults (fused ->
    # composite requeue), launch#1 OOM-faults the relaunch (lanes halved),
    # launch#2 runs the job to a verdict on the twice-degraded config.
    inj = faults.FaultInjector(
        faults.FaultSchedule.at(
            {"engine.advance": {0: "runtime"}, "engine.launch": {1: "oom"}}
        )
    )
    with faults.injected(inj):
        eng = SolverEngine(config=FUSED_SMALL, max_batch=8).start()
        try:
            j = eng.submit(HARD_9[0])
            assert j.wait(120), (j.error, j.last_fault)
            assert j.solved and j.error is None
            m = eng.metrics()["faults"]
            assert m["downgrades"]["fused_to_composite"] >= 1
            assert m["downgrades"]["lanes_halved"] >= 1
        finally:
            eng.stop(timeout=2)


def test_oom_on_multijob_group_halves_and_stays_transient():
    """An OOM on a multi-job launch must ride the lane-halving rung, NOT
    bisection: the halved width is pinned and becomes a per-flight cap
    (_launch_flights splits the requeued group at it), so the retry is a
    legal launch and every job solves with zero permanent classifications."""
    inj = faults.FaultInjector(
        faults.FaultSchedule.at({"engine.launch": {0: "oom"}})
    )
    with faults.injected(inj):
        eng = SolverEngine(config=SMALL, max_batch=8, batch_window_s=0.2).start()
        try:
            jobs = [eng.submit(p) for p in HARD_9[:4]]
            for j in jobs:
                assert j.wait(120), (j.error, j.last_fault)
                assert j.solved and j.error is None, j.error
            m = eng.metrics()["faults"]
            assert m["downgrades"]["lanes_halved"] >= 1
            assert m["bisections"] == 0, "transient OOM was bisected"
            assert m["permanent_failures"] == 0
        finally:
            eng.stop(timeout=2)


def test_poison_job_bisected_and_fails_alone():
    """A permanent fault that follows one job: the batch is bisected until
    the poison job is isolated — it fails alone, every batchmate completes,
    and the bisection is counted."""
    inj = faults.FaultInjector(poison_jobs=("poison-me",))
    with faults.injected(inj):
        # A wide batch window packs all six jobs into one launch group.
        eng = SolverEngine(config=SMALL, max_batch=8, batch_window_s=0.2).start()
        try:
            mates = [eng.submit(p) for p in HARD_9]
            poison = eng.submit(EASY_9, job_uuid="poison-me")
            mates.append(eng.submit(EASY_9))
            for j in mates:
                assert j.wait(120), (j.error, j.fault_retries)
                assert j.solved and j.error is None, j.error
            assert poison.wait(120)
            assert not poison.solved and poison.error, "poison job survived?"
            assert "[permanent]" in poison.error
            m = eng.metrics()["faults"]
            assert m["bisections"] >= 1, m
            assert m["permanent_failures"] == 1
            # Still serving afterwards.
            ok = eng.submit(EASY_9)
            assert ok.wait(60) and ok.solved
        finally:
            eng.stop(timeout=2)


def test_resident_breaker_opens_halfopens_closes():
    """The circuit breaker end to end on an injected clock (NO sleeps
    drive any transition): three consecutive rebuild failures open it
    (admission deflects to static flights, held jobs rerouted — none
    errored); after the cooldown the next admission half-opens it as the
    probe; the probe's first consumed chunk closes it."""
    clock = FakeClock()
    pol = faults.RecoveryPolicy(
        max_retries=10,
        rebuild_cooldown_s=0.0,
        breaker_failures=3,
        breaker_cooldown_s=60.0,  # only the fake clock can elapse this
        clock=clock,
    )
    inj = faults.FaultInjector(
        faults.FaultSchedule.at(
            {"resident.advance": {0: "runtime", 1: "preempt", 2: "oom"}}
        )
    )
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL, max_batch=8, resident=RC, recovery=pol
        ).start()
        try:
            rf = eng._resident_for(SUDOKU_9)
            assert rf is not None
            j1 = eng.submit(HARD_9[0])
            # Rebuild, rebuild, then the third failure opens the breaker;
            # the held job reroutes to a static flight and still solves.
            assert wait_for(lambda: rf.breaker.state == rf.breaker.OPEN)
            assert j1.wait(120), (j1.error, j1.fault_retries)
            assert j1.solved and j1.error is None
            assert rf.rebuilds == 2  # failures 1 and 2 requeued in place
            assert rf.requeued_static >= 1  # failure 3 rerouted
            # Open: admissions deflect to static flights (and solve there)
            # even under reject mode — a broken resident program is NOT
            # client backpressure, so no EngineSaturated/429 may surface.
            j2 = eng.submit(HARD_9[1], saturation="reject")
            assert j2.wait(120) and j2.solved
            assert rf.breaker_deflected >= 1
            assert eng.metrics()["faults"]["breaker"]["9x9"]["state"] == "open"
            before = rf.breaker.metrics()["transitions"]
            # Cooldown elapses ONLY via the fake clock: the next submit is
            # the half-open probe, its rebuilt flight serves it, and the
            # first consumed chunk closes the breaker.
            clock.advance(61.0)
            j3 = eng.submit(HARD_9[2])
            assert j3.wait(120) and j3.solved, (j3.error, j3.last_fault)
            assert wait_for(lambda: rf.breaker.state == rf.breaker.CLOSED)
            assert rf.breaker.metrics()["transitions"] >= before + 2
            admitted_before = rf.admitted
            j4 = eng.submit(EASY_9)
            # Admission really reopened: the submit was admitted RESIDENT
            # (a static-fallback solve would leave the counter unchanged).
            assert rf.admitted == admitted_before + 1
            assert j4.wait(60) and j4.solved
            assert eng.metrics()["faults"]["breaker"]["9x9"]["state"] == "closed"
        finally:
            eng.stop(timeout=2)


def test_bulk_endpoint_retries_transient_chunk_faults():
    """The HTTP bulk path: a transient fault on a bulk dispatch re-runs
    the chunk under the engine's recovery policy — the request still
    answers 200 with correct solutions, and the retry is counted."""
    import json
    import urllib.request

    from distributed_sudoku_solver_tpu.serving.http import ApiServer, StandaloneNode

    inj = faults.FaultInjector(
        faults.FaultSchedule.at({"bulk.dispatch": {0: "preempt"}})
    )
    with faults.injected(inj):
        eng = SolverEngine(config=SMALL, max_batch=8).start()
        node = StandaloneNode(engine=eng, address="127.0.0.1:test")
        api = ApiServer(node, host="127.0.0.1", port=0, solve_timeout_s=240).start()
        try:
            boards = [np.asarray(EASY_9).tolist()] * 3
            body = json.dumps({"boards": boards}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{api.port}/solve_batch",
                data=body,
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=240) as resp:
                out = json.loads(resp.read())
                assert resp.status == 200
            assert out["solved"] == 3, out
            assert eng.fault_bulk_retries >= 1
            assert inj.metrics()["injected"] == {"bulk.dispatch:preempt": 1}
        finally:
            api.stop()
            eng.stop(timeout=2)


def test_retry_budget_exhaustion_fails_job_with_classified_error():
    """A transient fault that never stops recurring must not retry forever:
    the per-job budget bounds it and the final error names both the budget
    and the last fault."""
    inj = faults.FaultInjector(
        faults.FaultSchedule.seeded(
            seed=1, rate=1.0, kinds=("preempt",), sites=("engine.launch",)
        )
    )
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL,
            max_batch=8,
            recovery=faults.RecoveryPolicy(max_retries=2),
        ).start()
        try:
            j = eng.submit(EASY_9)
            assert j.wait(60)
            assert not j.solved
            assert "retry budget exhausted after 2 retries" in j.error
            assert "UNAVAILABLE" in j.error  # the fault that killed it
            assert eng.metrics()["faults"]["budget_exhausted"] == 1
        finally:
            eng.stop(timeout=2)


@pytest.mark.slow
def test_chaos_soak_zero_lost_jobs_bit_identical():
    """Seeded chaos over a Poisson workload: a random schedule faulting
    ~10% of ALL serving dispatches, engine static + resident paths both
    live.  Zero lost jobs (every submit resolves), zero terminal errors,
    and solutions bit-identical to the fault-free run of the same
    workload."""
    from benchmarks.bench_poisson import poisson_load

    boards = [np.asarray(p) for p in HARD_9] * 6  # 18 jobs
    eng = SolverEngine(config=SMALL, max_batch=8, resident=RC).start()
    try:
        _, baseline = poisson_load(eng, boards, mean_gap_s=0.01, seed=13)
    finally:
        eng.stop(timeout=2)
    inj = faults.FaultInjector(faults.FaultSchedule.seeded(seed=41, rate=0.10))
    with faults.injected(inj):
        eng = SolverEngine(
            config=SMALL,
            max_batch=8,
            resident=RC,
            recovery=faults.RecoveryPolicy(
                max_retries=12, rebuild_cooldown_s=0.0, breaker_cooldown_s=0.05
            ),
        ).start()
        try:
            _, jobs = poisson_load(eng, boards, mean_gap_s=0.01, seed=13)
            m = eng.metrics()["faults"]
        finally:
            eng.stop(timeout=2)
    assert len(jobs) == len(baseline)
    for base, job in zip(baseline, jobs):
        assert job.done.is_set(), "lost job"
        assert job.solved and job.error is None, (job.error, job.last_fault)
        np.testing.assert_array_equal(job.solution, base.solution)
    assert sum(inj.metrics()["injected"].values()) >= 1
    assert m["budget_exhausted"] == 0 and m["permanent_failures"] == 0
