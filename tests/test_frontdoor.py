"""Front-door tests (ISSUE 14): the symmetry-canonical equivalence group,
the result cache, the difficulty probe, and the end-to-end routing
acceptance — a hard board solved once answers every symmetry-equivalent
resubmission from the cache with ZERO device fetches.

The canonical-form property lane is pure host numpy (no engine, no jax
dispatch); the routing lane boots real engines and — like every suite
that compiles resident programs — requests ``heavy_compile_guard`` once.
"""

import threading

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import (
    SUDOKU_4,
    SUDOKU_6,
    SUDOKU_9,
    SUDOKU_16,
)
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.frontdoor import cache as cache_mod
from distributed_sudoku_solver_tpu.serving.frontdoor.canonical import (
    apply_transform,
    canonicalize,
    random_transform,
    restore_solution,
)
from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
    FrontDoorConfig,
    probe_propagate,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import (
    EASY_9,
    HARD_9,
    make_puzzle,
)

SMALL = SolverConfig(min_lanes=8, stack_slots=24, max_steps=40_000)

#: A generated board the probe classifies easy-but-open (pinned by
#: test_probe_classifications below): the native-route fixture.
EASY_OPEN_SEED, EASY_OPEN_CLUES = 123, 30


def _easy_open_board() -> np.ndarray:
    return make_puzzle(SUDOKU_9, seed=EASY_OPEN_SEED, n_clues=EASY_OPEN_CLUES)


# -- equivalence-group property lane -------------------------------------------


def test_canonical_invariant_under_200_group_compositions():
    """ISSUE satellite: the canonical form is invariant under random
    compositions of the group generators — 200 deterministic draws
    (fuzz-seeded), spread over four base boards, each transform itself a
    composition of relabel/row/col/band/stack permutations + transpose,
    and half the draws compose TWO such elements."""
    rng = np.random.default_rng(0xF00D)
    boards = [np.asarray(EASY_9)] + [np.asarray(b) for b in HARD_9]
    for i in range(200):
        board = boards[i % len(boards)]
        want = canonicalize(board, SUDOKU_9)
        b2 = apply_transform(board, random_transform(SUDOKU_9, rng))
        if i % 2:
            b2 = apply_transform(b2, random_transform(SUDOKU_9, rng))
        got = canonicalize(b2, SUDOKU_9)
        assert got.digest == want.digest, f"composition {i} broke invariance"
        assert np.array_equal(got.grid, want.grid)


def test_inverse_transform_round_trips_solution_bit_exactly():
    """The cache contract end to end, without an engine: the entry filled
    from representative A and hit from representative B must hand B its
    own frame's solution bit-exactly."""
    from distributed_sudoku_solver_tpu import native

    board = np.asarray(HARD_9[0])
    if native.available():
        solution, _ = native.solve(board)
    else:  # pragma: no cover - no compiler in the container
        from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle

        solution, _ = solve_oracle(board)
    rng = np.random.default_rng(0xBEEF)
    cf_a = canonicalize(board, SUDOKU_9)
    canon_sol_a = apply_transform(solution, cf_a.transform)
    for i in range(20):
        tr = random_transform(SUDOKU_9, rng)
        board_b = apply_transform(board, tr)
        sol_b = apply_transform(solution, tr)
        cf_b = canonicalize(board_b, SUDOKU_9)
        # Same orbit, same entry: B's canonical solution IS A's (unique
        # puzzle -> unique canonical solution whatever the filler).
        assert np.array_equal(
            apply_transform(sol_b, cf_b.transform), canon_sol_a
        ), f"draw {i}: canonical solutions diverged"
        # And the stored canonical solution maps back to B's frame.
        restored = restore_solution(canon_sol_a, cf_b.transform)
        assert np.array_equal(restored.astype(np.int64), sol_b), (
            f"draw {i}: inverse transform broke bit-exactness"
        )
        assert restored[board_b != 0].tolist() == board_b[board_b != 0].tolist()


def test_canonically_distinct_boards_never_collide():
    """Distinct orbits -> distinct canonical grids -> distinct digests
    (sha256 over the canonical bytes; a collision would need sha256 to
    collide on 81-byte inputs)."""
    boards = [np.asarray(EASY_9)] + [np.asarray(b) for b in HARD_9]
    boards += [make_puzzle(SUDOKU_9, seed=s, n_clues=30) for s in range(40, 60)]
    forms = [canonicalize(b, SUDOKU_9) for b in boards]
    digests = {}
    for b, cf in zip(boards, forms):
        key = cf.grid.tobytes()
        if cf.digest in digests:
            assert digests[cf.digest] == key, "digest collision across orbits"
        digests[cf.digest] = key
    # The generated boards are distinct puzzles; at least most orbits
    # must be distinct (sanity that the test is not vacuous).
    assert len(set(digests)) >= 20


def test_canonicalize_policy_bounds():
    # 16x16: beyond the enumeration bound -> uncacheable by policy.
    assert canonicalize(np.zeros((16, 16), np.int64), SUDOKU_16) is None
    # Small geometries stay exact (4x4 has a transpose frame, 6x6 none).
    rng = np.random.default_rng(3)
    g4 = np.zeros((4, 4), np.int64)
    g4[0, 0], g4[1, 2] = 1, 2
    want4 = canonicalize(g4, SUDOKU_4)
    g6 = np.zeros((6, 6), np.int64)
    g6[0, 0], g6[3, 4] = 1, 5
    want6 = canonicalize(g6, SUDOKU_6)
    for _ in range(10):
        got4 = canonicalize(
            apply_transform(g4, random_transform(SUDOKU_4, rng)), SUDOKU_4
        )
        got6 = canonicalize(
            apply_transform(g6, random_transform(SUDOKU_6, rng)), SUDOKU_6
        )
        assert got4.digest == want4.digest
        assert got6.digest == want6.digest
    # Out-of-range cell values are a caller bug, not an orbit.
    bad = np.zeros((9, 9), np.int64)
    bad[0, 0] = 11
    with pytest.raises(ValueError):
        canonicalize(bad, SUDOKU_9)


# -- difficulty probe ----------------------------------------------------------


def test_probe_classifications():
    pr = probe_propagate(np.asarray(EASY_9), SUDOKU_9)
    assert pr.status == "solved"
    assert is_valid_solution(pr.solution)
    mask = np.asarray(EASY_9) != 0
    assert (pr.solution[mask] == np.asarray(EASY_9)[mask]).all()
    # The published hard boards stay open with a score far above the
    # default easy threshold (they must never route native by accident).
    for b in HARD_9[:2]:
        pr = probe_propagate(np.asarray(b), SUDOKU_9)
        assert pr.status == "open"
        assert pr.score > FrontDoorConfig().easy_score
    # The native-route fixture: open but comfortably under the threshold.
    pr = probe_propagate(_easy_open_board(), SUDOKU_9)
    assert pr.status == "open"
    assert 0 < pr.score <= FrontDoorConfig().easy_score
    # A contradiction is a PROOF of unsatisfiability.
    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    assert probe_propagate(bad, SUDOKU_9).status == "unsat"
    # Out-of-range values: 'open' (the device path keeps its behavior).
    weird = np.zeros((9, 9), np.int64)
    weird[0, 0] = 12
    assert probe_propagate(weird, SUDOKU_9).status == "open"


def test_probe_solution_is_the_unique_solution():
    """A probe-completed grid is forced cell by cell, so it must agree
    with the independent solver's answer exactly."""
    from distributed_sudoku_solver_tpu import native

    board = np.asarray(HARD_9[2])  # the 17-clue board: propagation-solved
    pr = probe_propagate(board, SUDOKU_9)
    assert pr.status == "solved"
    assert is_valid_solution(pr.solution)
    if native.available():
        sol, _ = native.solve(board)
        assert np.array_equal(sol, pr.solution)


# -- result cache unit lane ----------------------------------------------------


def _entry(verdict=cache_mod.SOLVED, raw="r0"):
    sol = None if verdict == cache_mod.UNSAT else np.ones((9, 9), np.int8)
    return cache_mod.CacheEntry(
        verdict=verdict, solution=sol, nodes=7, raw_digest=raw, route="device"
    )


def test_result_cache_lru_negative_and_dup_counters():
    c = cache_mod.ResultCache(capacity=2)
    c.store_entry("a", _entry(raw="ra"))
    c.store_entry("b", _entry(verdict=cache_mod.UNSAT, raw="rb"))
    assert len(c) == 2
    # Hit from the SAME representative: no canonical dup.
    assert c.lookup_entry("a", "ra").verdict == cache_mod.SOLVED
    assert c.metrics()["canonical_dups"] == 0
    # Hit from a different representative of the orbit: a canonical dup;
    # an unsat entry is a negative hit.
    assert c.lookup_entry("b", "OTHER").verdict == cache_mod.UNSAT
    m = c.metrics()
    assert m["canonical_dups"] == 1 and m["negative_hits"] == 1
    # LRU: 'a' was touched after 'b'... but 'b' was touched last; insert
    # evicts the least recently used ('a').
    c.store_entry("c", _entry(raw="rc"))
    assert c.lookup_entry("a", "ra") is None  # evicted
    assert c.lookup_entry("b", "rb") is not None
    m = c.metrics()
    assert m["evictions"] == 1 and m["misses"] == 1 and m["entries"] == 2


# -- end-to-end routing acceptance ---------------------------------------------


@pytest.fixture(scope="module")
def frontdoor_engine():
    # heavy_compile_guard is function-scoped and requested by the FIRST
    # test that drives this engine (the module's one heavy-compile site);
    # engine construction itself compiles nothing.
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        chunk_steps=8,
        resident=ResidentConfig(job_slots=4, gang_lanes=4, queue_depth=16),
        frontdoor=FrontDoorConfig(),
    ).start()
    yield eng
    eng.stop(timeout=5)


def test_routing_acceptance_end_to_end(
    heavy_compile_guard, frontdoor_engine, monkeypatch
):
    """The ISSUE acceptance pin, in one flow:

    1. a hard board solved once (device route, resident flight);
    2. resubmitted under a random symmetry transform it answers from the
       cache with ZERO device fetches (the round-8 ``host_fetch`` seam is
       wrapped and must not fire during the cached request) and the
       returned solution maps bit-exactly to the transformed frame;
    3. an easy board routes native, a hard board routes resident
       (device), each verdict bit-identical to the direct engine path
       (``frontdoor=False``).
    """
    import distributed_sudoku_solver_tpu.serving.engine as engine_mod

    eng = frontdoor_engine
    hard = np.asarray(HARD_9[0])

    # Direct path first (frontdoor=False): the bit-exactness oracle.
    direct = eng.submit(hard, frontdoor=False)
    assert direct.wait(300) and direct.solved, direct.error
    assert direct.route is None  # the bypass really bypassed

    # 1. Hard board through the front door: device route, resident
    #    admission (eligible plain submit on a resident-enabled engine).
    j_hard = eng.submit(hard)
    assert j_hard.wait(300) and j_hard.solved, j_hard.error
    assert j_hard.route == "device"
    assert np.array_equal(j_hard.solution, direct.solution)
    rm = eng.metrics()["resident"]["9x9"]
    assert rm["admitted"] >= 1, "hard board did not ride the resident flight"

    # 2. Symmetry-transformed resubmit: cache hit, zero device fetches.
    rng = np.random.default_rng(0xCAFE)
    tr = random_transform(SUDOKU_9, rng)
    transformed = apply_transform(hard, tr)
    fetches = []
    orig = engine_mod.host_fetch

    def counting(x, floor_s=0.0, tag="status"):
        fetches.append(tag)
        return orig(x, floor_s, tag)

    monkeypatch.setattr(engine_mod, "host_fetch", counting)
    j_cache = eng.submit(transformed)
    assert j_cache.wait(30) and j_cache.solved
    monkeypatch.setattr(engine_mod, "host_fetch", orig)
    assert j_cache.route == "cache"
    assert fetches == [], f"cached answer cost device fetches: {fetches}"
    # Bit-exact in the TRANSFORMED frame: the cached canonical solution
    # mapped through this request's own inverse transform.
    assert np.array_equal(
        j_cache.solution, apply_transform(direct.solution, tr)
    )
    assert is_valid_solution(j_cache.solution)

    # 3. Easy board: native route, verdict identical to the direct path.
    easy = _easy_open_board()
    direct_easy = eng.submit(easy, frontdoor=False)
    assert direct_easy.wait(300) and direct_easy.solved
    j_easy = eng.submit(easy.copy())
    assert j_easy.wait(60) and j_easy.solved, j_easy.error
    from distributed_sudoku_solver_tpu import native

    if native.available():
        assert j_easy.route == "native"
    assert is_valid_solution(j_easy.solution)
    # Unique puzzle (make_puzzle carves uniqueness-checked): any sound
    # engine returns THE solution.
    assert np.array_equal(j_easy.solution, direct_easy.solution)

    fd = eng.metrics()["frontdoor"]
    assert fd["routes"]["cache"] >= 1
    assert fd["routes"]["device"] >= 1
    assert fd["cache"]["hits"] >= 1
    assert fd["cache"]["canonical_dups"] >= 1


def test_propagation_and_negative_cache_routes(frontdoor_engine):
    eng = frontdoor_engine
    j = eng.submit(np.asarray(EASY_9))
    assert j.wait(30) and j.solved and j.route == "propagation"
    assert is_valid_solution(j.solution)
    # Proven-unsat boards cache as negative entries: second submission
    # of an EQUIVALENT board answers from the cache, still unsat.
    bad = np.asarray(EASY_9).copy()
    bad[0, 0], bad[0, 1] = 5, 5
    j1 = eng.submit(bad)
    assert j1.wait(30) and j1.unsat and j1.route == "propagation"
    tr = random_transform(SUDOKU_9, np.random.default_rng(1))
    j2 = eng.submit(apply_transform(bad, tr))
    assert j2.wait(30) and j2.unsat and j2.route == "cache"
    assert j2.solution is None
    # The engine verdict convention: unsat rides a COMPLETE refutation,
    # which cluster _Exec finalization reads off `exhausted` — without
    # it a cluster node turns a front-door 422 into a 500 (live /verify
    # regression).
    assert j1.exhausted and j2.exhausted


def test_frontdoor_stats_and_latency_histograms(frontdoor_engine):
    """Front-door-answered jobs count as the node's work (stats parity)
    and the per-route latency histograms are live."""
    eng = frontdoor_engine
    before = eng.stats()
    j = eng.submit(np.asarray(EASY_9))  # cache hit by now (earlier test)
    assert j.wait(30) and j.solved
    after = eng.stats()
    assert after["jobs_done"] == before["jobs_done"] + 1
    assert after["solved"] == before["solved"] + 1
    hist = eng.metrics()["hist"]
    assert "frontdoor_cache_ms" in hist or "frontdoor_propagation_ms" in hist
    assert "frontdoor_device_ms" in hist


def test_race_native_device_fallback_when_native_declines(monkeypatch):
    """race_native's seam contract: a native decline (no compiler) must
    fall through to the device entrant and still resolve the job with
    the right verdict."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine
    from distributed_sudoku_solver_tpu.serving.portfolio import race_native

    monkeypatch.setattr(native, "available", lambda: False)
    eng = SolverEngine(config=SMALL, max_batch=8).start()
    try:
        board = _easy_open_board()
        verdicts = []
        job = Job(uuid="race-fallback-test", grid=board, geom=SUDOKU_9)
        job.submitted_at = eng._clock()
        race_native(
            eng, job, head_start_s=0.05, on_verdict=lambda j: verdicts.append(j.route)
        )
        assert job.wait(300) and job.solved, job.error
        assert job.route == "device"
        assert verdicts == ["device"]
        assert is_valid_solution(job.solution)
    finally:
        eng.stop(timeout=5)


def test_race_native_late_win_counts_request_once(monkeypatch):
    """Review regression: a native win AFTER the device fallback has been
    submitted must not double-count the request — the fallback is a
    shadow job (engine accounting skips it); the race's hook counts the
    one request, and the wall lands in the winning route's histogram."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine
    from distributed_sudoku_solver_tpu.serving.portfolio import race_native

    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4).start()
    release = threading.Event()
    try:
        board = np.asarray(HARD_9[0])
        expected, _ = native.solve(board) if native.available() else (None, 0)
        if expected is None:
            pytest.skip("native solver unavailable")
        pacer = threading.Event()
        # Park the device loop in an exclusive section so the submitted
        # fallback provably cannot win — the ONLY ordering under test is
        # "native verdict lands after the fallback is in flight".
        exclusive = threading.Thread(
            target=lambda: eng.run_exclusive(lambda: release.wait(60)),
            daemon=True,
        )
        exclusive.start()
        pacer.wait(0.1)  # let the loop claim the exclusive section

        def slow_native(grid, geom):
            # Lose the head start on purpose: return only once the
            # device fallback is definitely queued.
            for _ in range(5000):
                if eng.busy_depth() > 0:
                    break
                pacer.wait(0.01)
            pacer.wait(0.05)
            return expected.copy(), 12345

        monkeypatch.setattr(native, "available", lambda: True)
        monkeypatch.setattr(native, "solve", slow_native)
        before = eng.stats()
        resolutions = []
        job = Job(uuid="race-late-win", grid=board, geom=SUDOKU_9)
        job.submitted_at = eng._clock()
        race_native(eng, job, head_start_s=0.05,
                    on_verdict=lambda j: resolutions.append(j.route))
        assert job.wait(300) and job.solved
        assert job.route == "native" and resolutions == ["native"]
        release.set()
        exclusive.join(60)
        # Let the cancelled shadow fallback drain, then pin the engine's
        # books: the shadow resolution added NOTHING.
        for _ in range(200):
            if eng.busy_depth() == 0:
                break
            pacer.wait(0.05)
        after = eng.stats()
        assert after["jobs_done"] == before["jobs_done"]
        assert after["solved"] == before["solved"]
    finally:
        release.set()
        eng.stop(timeout=5)


def test_race_native_fallback_inherits_deadline(monkeypatch):
    """Review regression: deadline_s survives the native route — the
    shadow fallback inherits the outer job's absolute deadline, so a
    caller's wall-clock budget is enforced even when the native entrant
    declines and the board lands on a device flight."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.serving.engine import Job, SolverEngine
    from distributed_sudoku_solver_tpu.serving.portfolio import race_native

    monkeypatch.setattr(native, "available", lambda: False)
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4).start()
    try:
        job = Job(uuid="race-deadline", grid=np.asarray(HARD_9[0]), geom=SUDOKU_9)
        job.submitted_at = eng._clock()
        job.deadline = eng._clock() - 1.0  # already expired
        race_native(eng, job, head_start_s=0.01)
        assert job.wait(60), "expired fallback never resolved"
        assert job.error == "deadline expired", (job.error, job.solved)
    finally:
        eng.stop(timeout=5)


def test_route_commit_skipped_when_placement_fails():
    """Review regression: a device-routed submit that fails placement
    (here: engine stopped; the saturation-429 path is the same seam)
    must not inflate the device-route counters or park a cache-fill
    entry for a job that will never run."""
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine

    eng = SolverEngine(config=SMALL, max_batch=8, frontdoor=FrontDoorConfig()).start()
    eng.stop(timeout=5)
    fd = eng.frontdoor
    before = fd.metrics()
    with pytest.raises(RuntimeError):
        eng.submit(np.asarray(HARD_9[0]))
    after = fd.metrics()
    assert after["routes"]["device"] == before["routes"]["device"]
    assert after["probe"]["hard"] == before["probe"]["hard"]
    assert after["pending_fills"] == 0


def test_cli_frontdoor_flags():
    from distributed_sudoku_solver_tpu.cli import build_parser, make_engine

    ap = build_parser()
    args = ap.parse_args(["--no-frontdoor"])
    eng = make_engine(args)
    try:
        assert eng.frontdoor is None
    finally:
        eng.stop(timeout=1)
    args = ap.parse_args(["--cache-entries", "128", "--easy-score", "10"])
    eng = make_engine(args)
    try:
        assert eng.frontdoor is not None
        assert eng.frontdoor.cache.capacity == 128
        assert eng.frontdoor.config.easy_score == 10
    finally:
        eng.stop(timeout=1)


# -- bench / regress satellites ------------------------------------------------


def test_bench_mix_parsing_and_corpus_determinism():
    import benchmarks.bench_poisson as bp

    mix = bp.parse_mix("easy:3,hard:2,repeat:4")
    assert mix == {"easy": 3, "hard": 2, "repeat": 4}
    with pytest.raises(SystemExit):
        bp.parse_mix("easy:3,weird:2")
    boards_a, tiers_a = bp.mixed_corpus(mix, seed=7)
    boards_b, tiers_b = bp.mixed_corpus(mix, seed=7)
    assert tiers_a == tiers_b and len(boards_a) == 9
    assert all(np.array_equal(x, y) for x, y in zip(boards_a, boards_b))
    # Repeats are symmetry transforms of already-sent boards: same orbit
    # as some earlier board, and (generically) not byte-identical.
    sent_digests = []
    for b, tier in zip(boards_a, tiers_a):
        cf = canonicalize(np.asarray(b), SUDOKU_9)
        if tier == "repeat":
            assert cf.digest in sent_digests, "repeat left its source orbit"
        sent_digests.append(cf.digest)


def test_regress_mix_mismatch_is_non_comparable():
    import benchmarks.regress as regress

    perc = {"p50_ms": 10.0, "p95_ms": 20.0, "p99_ms": 30.0, "mean_ms": 12.0,
            "jobs": 4}
    def art(mix=None):
        params = {"jobs": 4, "mean_gap_ms": 50.0, "handicap_ms": 50.0,
                  "chunk_steps": 8, "seed": 7}
        if mix:
            params["mix"] = mix
        return {"schema": regress.SCHEMA, "params": params,
                "static": dict(perc), "resident": dict(perc)}

    rep = regress.compare(art(), art("easy:2,hard:1,repeat:1"))
    assert not rep["comparable"]
    assert any("mix" in e for e in rep["errors"])
    rep = regress.compare(art("easy:2"), art("easy:2"))
    assert rep["comparable"] and not rep["regressions"]
    # And the CLI surfaces it as exit 2 (non-comparable, not regression).
    import json
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        old_p, new_p = f"{d}/old.json", f"{d}/new.json"
        json.dump(art(), open(old_p, "w"))
        json.dump(art("easy:1,hard:1"), open(new_p, "w"))
        assert regress.main([old_p, new_p]) == 2
