"""Round-15 compile/recompile watch (obs/compilewatch.py).

Three layers:

* **Unit lane** — fake program registries on a fake clock: attribution
  pairs event durations with cache growth (counts exact, unregistered
  compiles bucketed), warmup gates the alarm, the recompile dump is
  edge-triggered (one per excursion) and re-arms after a quiet period.
* **Live lane** — a real engine under the watch: the serving workload's
  compiles land under display names on ``/metrics`` (the cost plane and
  efficiency gauge ride along), and a deliberately forced program change
  after warmup fires EXACTLY one ``recompile`` flight-recorder dump,
  re-armed after recovery (the ISSUE-12 acceptance pin).  The
  one-compile-per-program half of the acceptance lives in test_jaxck's
  retrace guard, which now runs ON this seam.
* **Microcheck** — with nothing installed, the watch's surfaces are
  provably unreachable (exploding monkeypatches) and a solve still
  works: the disabled path is one global read + one branch.
"""

import json
import logging
import os

import pytest

from distributed_sudoku_solver_tpu.obs import compilewatch, critpath, trace
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
EV = compilewatch.BACKEND_COMPILE_EVENT


@pytest.fixture(autouse=True)
def _clean_seams():
    yield
    compilewatch.install(None)
    critpath.install(None)
    trace.install(None)


class _FakeProg:
    """Quacks like a jit function for the attribution poll."""

    def __init__(self, n=0):
        self.n = n

    def _cache_size(self):
        return self.n


# -- unit lane -----------------------------------------------------------------


def test_attribution_counts_walls_and_unregistered():
    t = [0.0]
    a, b = _FakeProg(), _FakeProg()
    w = compilewatch.CompileWatch(
        programs={"prog_a": a, "prog_b": b}, warmup_s=100.0,
        clock=lambda: t[0],
    )
    # Real ordering: the event for compile N fires BEFORE N's cache
    # insertion.  a compiles (event, then insert), then b twice.
    w.on_duration(EV, 0.5)
    a.n += 1
    w.on_duration(EV, 0.25)
    b.n += 1
    w.on_duration(EV, 0.125)
    b.n += 1
    m = w.metrics()  # the read polls outstanding attribution
    assert m["programs"]["prog_a"]["count"] == 1
    assert m["programs"]["prog_a"]["wall_ms_total"] == pytest.approx(500.0)
    assert m["programs"]["prog_b"]["count"] == 2
    assert m["programs"]["prog_b"]["wall_ms_total"] == pytest.approx(375.0)
    assert m["compiles_total"] == 3
    assert m["recompiles_total"] == 0  # all inside warmup
    # A compile no registered program accounts for -> unregistered, but
    # only after SURVIVING one attribution pass (the first read could be
    # racing a registered compile's cache insertion — see the race
    # regression below).
    w.on_duration(EV, 0.0625)
    m = w.metrics()
    assert compilewatch.UNREGISTERED not in m["programs"]
    m = w.metrics()
    assert m["programs"][compilewatch.UNREGISTERED]["count"] == 1
    # Unrelated duration events are ignored; cache events counted.
    w.on_duration("/jax/core/compile/jaxpr_trace_duration", 9.0)
    w.on_event("/jax/compilation_cache/cache_hits")
    m = w.metrics()
    assert m["compiles_total"] == 4
    assert m["cache"]["persistent_cache_hits"] == 1


def test_scrape_racing_cache_insertion_never_misattributes(tmp_path):
    """Review-round regression: the backend-compile event fires BEFORE
    the program's cache insertion.  A /metrics scrape landing in that
    window must neither bucket the compile as `unregistered` nor fire a
    phantom post-warmup recompile alarm — the pending pairs with the
    growth at the next pass, counts stay exact."""
    t = [0.0]
    rec = trace.TraceRecorder(clock=lambda: t[0], dump_dir=str(tmp_path))
    a = _FakeProg()
    w = compilewatch.CompileWatch(
        programs={"prog_a": a}, warmup_s=0.0, clock=lambda: t[0]
    )
    with trace.installed(rec):
        t[0] = 1.0  # warmup over: a misattribution would ALARM here
        w.on_duration(EV, 0.5)  # event fired, insertion not yet visible
        m = w.metrics()  # the racing scrape
        assert compilewatch.UNREGISTERED not in m["programs"], m
        a.n += 1  # the insertion lands
        m = w.metrics()
        assert m["programs"]["prog_a"]["count"] == 1
        assert m["programs"]["prog_a"]["wall_ms_total"] == pytest.approx(500.0)
        assert compilewatch.UNREGISTERED not in m["programs"]
        assert m["compiles_total"] == 1
        # The (real) recompile alarmed for prog_a, not a phantom twin.
        assert m["programs"]["prog_a"].get("recompiles") == 1
        dumps = [f for f in os.listdir(tmp_path) if "recompile" in f]
        assert len(dumps) == 1


def test_efficiency_suppressed_on_mixed_shapes():
    """Review-round regression: lifetime round totals span every flight
    shape, so once two shapes of the advance program captured cost the
    gauge must refuse to price them with one shape's flops."""
    w = compilewatch.CompileWatch(programs={}, warmup_s=1e9)

    class _Lowered:
        def cost_analysis(self):
            return {"flops": 100.0, "bytes accessed": 10.0}

    name = compilewatch.ADVANCE_STATUS
    w.capture_cost(name, (9, 128), _Lowered, geometry="9x9")
    eff = w.efficiency(name, rounds=1000, wall_s=1.0)
    assert eff["achieved_gflops_per_s"] > 0
    w.capture_cost(name, (16, 256), _Lowered, geometry="16x16")
    eff = w.efficiency(name, rounds=1000, wall_s=1.0)
    assert eff == {
        "program": "advance_status",
        "suppressed": "mixed_shapes",
        "shapes_captured": 2,
    }


def test_warmup_edge_triggered_dump_and_rearm(tmp_path, caplog):
    t = [0.0]
    rec = trace.TraceRecorder(clock=lambda: t[0], dump_dir=str(tmp_path))
    a = _FakeProg()
    w = compilewatch.CompileWatch(
        programs={"prog_a": a}, warmup_s=10.0, rearm_s=60.0,
        clock=lambda: t[0],
    )
    with trace.installed(rec):
        # Inside warmup: expected, no alarm.
        w.on_duration(EV, 0.1)
        a.n += 1
        w.poll()
        assert w.metrics()["recompiles_total"] == 0

        # After warmup: first unexpected recompile -> log + ONE dump.
        t[0] = 20.0
        with caplog.at_level(logging.WARNING):
            w.on_duration(EV, 0.2)
            a.n += 1
            w.poll()
        assert any(
            "[compile prog_a]" in r.getMessage() for r in caplog.records
        ), "recompile alarm must log [compile <program>]"
        m = w.metrics()
        assert m["recompiles_total"] == 1
        assert m["programs"]["prog_a"]["recompiles"] == 1
        assert m["dumps"] == 1 and m["armed"] is False
        dumps = [f for f in os.listdir(tmp_path) if "recompile" in f]
        assert len(dumps) == 1
        doc = json.loads((tmp_path / dumps[0]).read_text())
        assert doc["metrics"]["program"] == "prog_a"

        # Same excursion (still inside rearm_s): counted, NOT dumped.
        t[0] = 30.0
        w.on_duration(EV, 0.2)
        a.n += 1
        w.poll()
        assert w.metrics()["recompiles_total"] == 2
        assert len([f for f in os.listdir(tmp_path) if "recompile" in f]) == 1

        # Recovery: rearm_s of quiet re-arms; the next excursion dumps.
        t[0] = 30.0 + 61.0
        assert w.metrics()["armed"] is True  # reads apply the re-arm edge
        w.on_duration(EV, 0.2)
        a.n += 1
        w.poll()
        assert len([f for f in os.listdir(tmp_path) if "recompile" in f]) == 2
        # The alarm also leaves a trace event behind for the timeline.
        assert any(s["name"] == "compile" for s in rec.spans())


def test_seal_ends_warmup_immediately():
    t = [0.0]
    a = _FakeProg()
    w = compilewatch.CompileWatch(
        programs={"prog_a": a}, warmup_s=1e9, clock=lambda: t[0]
    )
    assert not w.warmup_over()
    w.seal()
    assert w.warmup_over()
    w.on_duration(EV, 0.1)
    a.n += 1
    assert w.metrics()["recompiles_total"] == 1


# -- live lane -----------------------------------------------------------------


def test_live_workload_exports_per_program_counts_and_cost(
    heavy_compile_guard,
):
    """A real engine under the watch: per-program compile counts appear
    under manifest display names in /metrics' `compile` section, and the
    cost plane captures the advance program's per-round flops with a
    live efficiency gauge (ceiling ratio when peak_gflops is set)."""
    watch = compilewatch.CompileWatch(warmup_s=3600.0, peak_gflops=100.0)
    with compilewatch.installed(watch):
        eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4).start()
        try:
            j = eng.submit(HARD_9[1])
            assert j.wait(180) and j.solved, j.error
            m = eng.metrics()
        finally:
            eng.stop(timeout=2)
    sec = m["compile"]
    assert sec["registered"] == 29  # every ENTRY_POINTS program resolved
    assert sec["recompiles_total"] == 0 and sec["armed"] is True
    # Display names are the manifest's shared vocabulary.  In a crowded
    # pytest process the serving set may be cache-warm (counts then stay
    # 0 and the program is absent) — but ANY compile this process paid
    # here must be attributed, and the status-advance program's cost
    # model is captured regardless of cache warmth.
    for name in sec["programs"]:
        assert name == compilewatch.UNREGISTERED or name in {
            e.get("display") for e in _manifest_entries()
        }, name
    cost = m["cost"]["programs"]["advance_status"]
    assert cost["flops"] > 0 and cost["bytes_accessed"] > 0
    assert cost["geometry"] == "9x9"
    eff = m["cost"]["efficiency"]
    assert eff["program"] == "advance_status"
    assert eff["achieved_gflops_per_s"] > 0
    assert eff["peak_gflops"] == 100.0
    assert 0 < eff["device_efficiency"] < 1


def _manifest_entries():
    from distributed_sudoku_solver_tpu.analysis import manifest

    return manifest.ENTRY_POINTS


def test_forced_program_change_fires_exactly_one_recompile_dump(
    tmp_path,
):
    """The ISSUE-12 acceptance: after warmup, a deliberately forced
    program change (a fresh static config — exactly what an HLO change
    does to the XLA cache) fires EXACTLY one recompile flight-recorder
    dump for the whole storm, and the alarm re-arms after recovery."""
    t = [0.0]
    rec = trace.TraceRecorder(dump_dir=str(tmp_path))
    watch = compilewatch.CompileWatch(
        warmup_s=100.0, rearm_s=60.0, clock=lambda: t[0]
    )
    with trace.installed(rec), compilewatch.installed(watch):
        eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=8).start()
        try:
            # Warmup: the serving set compiles (or is cache-warm).
            j = eng.submit(EASY_9)
            assert j.wait(180) and j.solved, j.error
            assert watch.metrics()["recompiles_total"] == 0

            # Warmup over; force a program change: a private static
            # config nothing else in the suite uses recompiles the
            # whole flight set — MANY recompile events, ONE dump.
            t[0] = 200.0
            j = eng.submit(
                EASY_9, config=SolverConfig(min_lanes=8, stack_slots=19)
            )
            assert j.wait(240) and j.solved, j.error
            m = watch.metrics()
            assert m["recompiles_total"] >= 2, m
            dumps = [f for f in os.listdir(tmp_path) if "recompile" in f]
            assert len(dumps) == 1, dumps
            assert m["armed"] is False

            # Recovery (a quiet rearm_s), then a second forced change:
            # the re-armed alarm dumps exactly once more.
            t[0] = 200.0 + 61.0
            assert watch.metrics()["armed"] is True
            j = eng.submit(
                EASY_9, config=SolverConfig(min_lanes=8, stack_slots=21)
            )
            assert j.wait(240) and j.solved, j.error
            assert watch.metrics()["recompiles_total"] >= 4
            dumps = [f for f in os.listdir(tmp_path) if "recompile" in f]
            assert len(dumps) == 2, dumps
        finally:
            eng.stop(timeout=2)


# -- microcheck ----------------------------------------------------------------


def test_disabled_seams_are_one_global_read(monkeypatch):
    """With no watch/monitor installed, none of the new surfaces may be
    reached from a serving solve: the guard is `active() is None` and
    everything else lives behind it."""
    assert compilewatch.active() is None
    assert critpath.active() is None

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("observability surface reached while disabled")

    monkeypatch.setattr(compilewatch.CompileWatch, "on_duration", boom)
    monkeypatch.setattr(compilewatch.CompileWatch, "on_event", boom)
    monkeypatch.setattr(compilewatch.CompileWatch, "capture_cost", boom)
    monkeypatch.setattr(compilewatch.CompileWatch, "metrics", boom)
    monkeypatch.setattr(critpath.CritPathMonitor, "observe_job", boom)
    monkeypatch.setattr(critpath.CritPathMonitor, "metrics", boom)
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=4).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(180) and j.solved, j.error
        m = eng.metrics()
        assert "compile" not in m and "cost" not in m and "critpath" not in m
    finally:
        eng.stop(timeout=2)
