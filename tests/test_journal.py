"""Durable job lifecycle (ISSUE 20): crash-safe WAL, graceful drain with
peer handoff, restart-under-chaos.

Three layers, mirroring the feature's own:

* **Journal unit tests** — the WAL contract with no engine attached:
  accept/resolve round-trip, torn-tail recovery, deterministic replay
  order, compaction bounding disk, and the ``journal.append`` /
  ``journal.fsync`` fault sites degrading to non-durable WITHOUT ever
  failing the accept path (the satellite-3 doctrine).
* **Engine lifecycle** — the WAL promise (accepted on disk before submit
  returns), verdicts discharging entries, idempotent client resubmit
  (no double solve, no double stats), the drain ladder under load, and
  restart replay through the normal submit seam.
* **Simnet cluster lane** — drain handing unstarted jobs to a
  gossip-healthy peer over the existing TASK frame, and the seeded
  kill/restart chaos soak: a node dies mid-flight (its pending resolve
  buffer LOST, exactly a crash), reboots on the same address with the
  same journal directory, replays, and every accepted job ends with a
  verdict bit-identical to the fault-free oracle.

The crash primitive is deliberately brutal: stop the batcher without the
final drain (``shutdown()`` would flush — a crash does not), then detach
the journal so post-mortem resolutions never reach the WAL.  What
survives is what a real ``kill -9`` would leave on disk.
"""

import json
import os
import threading

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig, ClusterNode
from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import (
    EngineDraining,
    Job as EngineJob,
    SolverEngine,
)
from distributed_sudoku_solver_tpu.serving.faults import FaultInjector, FaultSchedule
from distributed_sudoku_solver_tpu.serving.frontdoor.cache import ResultCache
from distributed_sudoku_solver_tpu.serving.journal import Journal, read_segment
from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

from tests.test_cluster import a_geom, oracle_solve_fn

EASY = np.asarray(EASY_9, np.int32)


# -- journal unit layer -------------------------------------------------------


def test_wal_accept_resolve_roundtrip(tmp_path):
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    jr.record_accepted("u1", grid=EASY, deadline_s=2.5)
    jr.record_accepted("u2", grid=EASY)
    jr.record_resolved("u1", {"solved": True, "nodes": 7})
    jr.sync_now()
    un = jr.unresolved()
    assert [ev["uuid"] for ev in un] == ["u2"]
    assert un[0]["grid"] == EASY.tolist()
    m = jr.metrics()
    assert m["accepted"] == 2 and m["resolved"] == 1 and m["durable"]
    jr.shutdown()
    # Reopen: state reconstructed from segments alone.
    jr2 = Journal(str(tmp_path))
    assert [ev["uuid"] for ev in jr2.unresolved()] == ["u2"]
    jr2.shutdown()


def test_torn_tail_truncation_recovers_cleanly(tmp_path):
    """A crash mid-write loses at most the final line; recovery skips it
    and keeps every complete record (satellite 3)."""
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    jr.record_accepted("u1", grid=EASY)
    jr.record_accepted("u2", grid=EASY)
    jr.sync_now()
    jr.shutdown()
    segs = sorted(
        n for n in os.listdir(tmp_path) if n.startswith("wal-")
    )
    # Tear the tail of the newest non-empty segment: half a JSON record,
    # no trailing newline — the worst a crash mid-write leaves behind.
    target = next(
        os.path.join(tmp_path, n)
        for n in reversed(segs)
        if os.path.getsize(os.path.join(tmp_path, n)) > 0
    )
    with open(target, "a", encoding="utf-8") as fh:
        fh.write('{"kind": "resolved", "uuid": "u')
    jr2 = Journal(str(tmp_path))
    assert {ev["uuid"] for ev in jr2.unresolved()} == {"u1", "u2"}, (
        "torn tail corrupted recovery"
    )
    # The reborn journal appends to a FRESH segment, never the torn one.
    jr2.record_accepted("u3", grid=EASY)
    jr2.sync_now()
    assert {ev["uuid"] for ev in jr2.unresolved()} == {"u1", "u2", "u3"}
    jr2.shutdown()


def test_append_fault_degrades_to_non_durable_never_raises(tmp_path, caplog):
    """Disk-full doctrine (satellite 3): an injected ``journal.append``
    failure flips the journal non-durable with a loud counter and a
    ``[journal]`` log line — and the accept path NEVER sees it."""
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    with faults.injected(
        FaultInjector(FaultSchedule.at({"journal.append": {0: "runtime"}}))
    ):
        with caplog.at_level("ERROR"):
            jr.record_accepted("u1", grid=EASY)  # must not raise
    assert not jr.durable
    m = jr.metrics()
    assert m["append_failures"] == 1
    assert any("DEGRADED" in r.getMessage() for r in caplog.records)
    # Subsequent appends are dropped (counted), still never raising.
    jr.record_accepted("u2", grid=EASY)
    assert jr.metrics()["dropped_non_durable"] >= 1
    jr.shutdown()


def test_fsync_fault_degrades_to_non_durable(tmp_path):
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    jr.record_accepted("u1", grid=EASY)
    with faults.injected(
        FaultInjector(FaultSchedule.at({"journal.fsync": {0: "runtime"}}))
    ):
        jr.sync_now()  # must not raise
    assert not jr.durable
    assert jr.metrics()["fsync_failures"] == 1
    jr.record_accepted("u2", grid=EASY)  # accept path still silent
    jr.shutdown()


def test_two_recover_scans_byte_identical(tmp_path):
    """Deterministic replay (satellite 3): two independent scans of the
    same directory produce byte-identical replay sets, in accept order."""
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    for i in range(6):
        jr.record_accepted(f"u{i}", grid=EASY, deadline_s=float(i))
    jr.record_resolved("u1", {"solved": True})
    jr.record_resolved("u4", {"unsat": True})
    jr.sync_now()
    jr.shutdown()
    scans = []
    for _ in range(2):
        j = Journal(str(tmp_path))
        scans.append(json.dumps(j.unresolved(), sort_keys=True).encode())
        j.shutdown()
    assert scans[0] == scans[1]
    assert [ev["uuid"] for ev in Journal(str(tmp_path)).unresolved()] == [
        "u0", "u2", "u3", "u5",
    ]


def test_compaction_bounds_disk(tmp_path):
    jr = Journal(
        str(tmp_path), segment_bytes=4096, fsync_interval_s=60.0,
        compact_min_resolved=1,
    )
    for i in range(64):
        jr.record_accepted(f"u{i}", grid=EASY)
        jr.record_resolved(f"u{i}", {"solved": True})
    jr.record_accepted("live", grid=EASY)
    jr.compact()
    assert jr.metrics()["compactions"] >= 1
    assert jr.metrics()["segments_removed"] >= 1
    segs = [n for n in os.listdir(tmp_path) if n.startswith("wal-")]
    assert len(segs) == 1, f"compaction left segments behind: {segs}"
    assert [ev["uuid"] for ev in jr.unresolved()] == ["live"]
    jr.shutdown()
    # The compacted directory still recovers.
    jr2 = Journal(str(tmp_path))
    assert [ev["uuid"] for ev in jr2.unresolved()] == ["live"]
    jr2.shutdown()


def test_frontdoor_hot_set_snapshot_roundtrip(tmp_path):
    """The L1 sidecar: drain exports the hottest entries, boot re-imports
    them warm (order-preserving, malformed entries skipped)."""
    jr = Journal(str(tmp_path))
    cache = ResultCache(capacity=16)
    sol = solve_oracle(EASY, a_geom(EASY))
    from distributed_sudoku_solver_tpu.serving.frontdoor.cache import CacheEntry

    cache.store_entry("d1", CacheEntry("solved", sol.astype(np.int8), 7, "r1", "device"))
    cache.store_entry("d2", CacheEntry("unsat", None, 3, "r2", "propagation"))
    jr.save_frontdoor(cache.export_hot())
    jr.shutdown()

    jr2 = Journal(str(tmp_path))
    cold = ResultCache(capacity=16)
    n = cold.import_hot(jr2.load_frontdoor() + ["garbage", {"digest": "x"}])
    assert n == 2
    hit = cold.lookup_entry("d1", "r1")
    assert hit is not None and hit.verdict == "solved"
    assert np.array_equal(hit.solution, sol.astype(np.int8))
    assert cold.lookup_entry("d2", "r2").verdict == "unsat"
    jr2.shutdown()


# -- engine lifecycle layer ---------------------------------------------------


def _engine(journal=None, solve_fn=None):
    return SolverEngine(
        solve_fn=solve_fn or oracle_solve_fn(), batch_window_s=0.001,
        journal=journal,
    ).start()


def test_wal_promise_precedes_answer_and_verdict_discharges(tmp_path):
    """The tentpole invariant: the accepted record is ON DISK before
    submit() returns (synchronous append), and a real verdict discharges
    it via the batcher."""
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    eng = _engine(journal=jr)
    try:
        job = eng.submit(EASY, job_uuid="wal-1")
        # Before the job resolves: the promise is already durable-bound.
        on_disk = [
            ev
            for n in sorted(os.listdir(tmp_path)) if n.startswith("wal-")
            for ev in read_segment(os.path.join(tmp_path, str(n)))
        ]
        assert any(
            ev["kind"] == "accepted" and ev["uuid"] == "wal-1"
            for ev in on_disk
        ), "accepted record not written before submit returned"
        assert job.wait(60) and job.solved
        jr.sync_now()
        assert jr.unresolved() == []
        assert eng.metrics()["journal"]["resolved"] >= 1
        assert eng.metrics()["lifecycle"]["state"] == 0  # serving
    finally:
        eng.stop()
        jr.shutdown()


def test_idempotent_resubmit_returns_verdict_without_double_count(tmp_path):
    """Satellite 2: a duplicate client uuid returns the existing job —
    same verdict object, no second solve, no double counting in stats or
    the WAL."""
    jr = Journal(str(tmp_path), fsync_interval_s=60.0)
    eng = _engine(journal=jr)
    try:
        j1 = eng.submit(EASY, job_uuid="dup-1")
        assert j1.wait(60) and j1.solved
        solved = eng.stats()["solved"]
        j2 = eng.submit(EASY, job_uuid="dup-1")
        assert j2 is j1, "resubmit did not dedupe to the in-registry job"
        assert eng.stats()["solved"] == solved, "duplicate was double-counted"
        assert jr.metrics()["accepted"] == 1, "duplicate re-journaled"
        # In-flight dedupe too: a second uuid'd job, resubmitted before
        # waiting, is the same handle.
        j3 = eng.submit(np.asarray(HARD_9[0], np.int32), job_uuid="dup-2")
        assert eng.submit(np.asarray(HARD_9[0], np.int32), job_uuid="dup-2") is j3
        assert j3.wait(120)
    finally:
        eng.stop()
        jr.shutdown()


def test_error_terminal_evicted_so_retry_runs_fresh():
    """An infra-errored terminal must NOT satisfy a resubmit: the registry
    evicts it at lookup and the retry solves fresh."""
    eng = _engine()
    try:
        dead = EngineJob(uuid="err-1", grid=EASY, geom=a_geom(EASY))
        dead.error = "retry budget exhausted: [oom]"
        dead.done.set()
        with eng._lock:
            eng._jobs_by_uuid["err-1"] = dead
        assert eng._dup_job("err-1") is None, "error terminal served as dup"
        j = eng.submit(EASY, job_uuid="err-1")
        assert j is not dead
        assert j.wait(60) and j.solved
    finally:
        eng.stop()


def test_drain_under_load_journals_unstarted_and_replays_on_restart(tmp_path):
    """The drain ladder with no peers: in-flight work finishes (or is left
    to), unstarted jobs journal for restart, admission closes with a
    machine-readable refusal, and the restarted engine replays exactly
    the journaled set — zero accepted-job loss."""
    gate = threading.Event()
    entered = threading.Event()
    base = oracle_solve_fn()

    def gated(grids, geom, cfg):
        entered.set()
        gate.wait(600)
        return base(grids, geom, cfg)

    jr = Journal(str(tmp_path), fsync_interval_s=0.01)
    eng = _engine(journal=jr, solve_fn=gated)
    try:
        j0 = eng.submit(EASY, job_uuid="fly-0")
        # Wait until the solve has STARTED (batch window closed) so the
        # jobs below cannot be swept into j0's batch.
        assert entered.wait(30)
        queued = [
            eng.submit(EASY, job_uuid=f"queued-{i}") for i in range(3)
        ]
        res = eng.drain(timeout=0.1)
        assert res["state"] == "drained"
        assert res["journaled"] == 3, res
        # The gated job solves synchronously on the device loop (legacy
        # solve_fn path — no flight record), so ``leftover`` cannot see
        # it; the invariant that matters is below: it FINISHES and its
        # WAL entry discharges.
        for q in queued:
            assert q.done.is_set() and "draining" in (q.error or "")
        with pytest.raises(EngineDraining) as ei:
            eng.submit(EASY)
        assert ei.value.state == "drained"
        assert eng.metrics()["lifecycle"]["state"] == 2
        # A polling client still gets its answer while drained.
        assert eng.submit(EASY, job_uuid="fly-0") is j0
        # The in-flight job completes after the gate opens: finished, not
        # lost, and its WAL entry discharges.
        gate.set()
        assert j0.wait(60) and j0.solved
        jr.sync_now()
        assert {ev["uuid"] for ev in jr.unresolved()} == {
            "queued-0", "queued-1", "queued-2",
        }
    finally:
        gate.set()
        eng.stop()
        jr.shutdown()

    # Restart over the same directory: replay through the normal submit
    # seam, every journaled job ends in a real verdict.
    jr2 = Journal(str(tmp_path), fsync_interval_s=0.01)
    eng2 = _engine(journal=jr2)
    try:
        n = eng2.recover()
        assert n == 3
        assert eng2.metrics()["lifecycle"]["recovered_jobs"] == 3
        for i in range(3):
            j = eng2._dup_job(f"queued-{i}")
            assert j is not None and j.wait(60) and j.solved
        jr2.sync_now()
        assert jr2.unresolved() == []
    finally:
        eng2.stop()
        jr2.shutdown()


# -- simnet cluster lane ------------------------------------------------------

SIM = ClusterConfig(
    heartbeat_s=0.25,
    fail_factor=8.0,
    io_timeout_s=2.0,
    needwork=False,
    progress_interval_s=0.0,
    retry_delay_s=0.1,
    tombstone_probe_s=600.0,
)


@pytest.fixture
def net():
    n = SimNet()
    n.nodes = []
    yield n
    for node in n.nodes:
        node.kill()
        node.engine.stop(timeout=1)
    n.close()


def sim_node(net, anchor=None, config=SIM, engine=None, port=0):
    eng = engine or SolverEngine(
        solve_fn=oracle_solve_fn(), batch_window_s=0.001
    ).start()
    node = ClusterNode(
        eng, port=port, anchor=anchor, config=config,
        transport=net.transport(), clock=net.clock,
    ).start()
    net.nodes.append(node)
    return node


def _crash(node, jr):
    """The crash-restart primitive's first half: network death + WAL
    batcher death WITHOUT the final drain — the in-memory pending resolve
    buffer is LOST, exactly as a ``kill -9`` would lose it.  The journal
    directory on disk is what the reborn node gets."""
    node.kill()
    jr._stop.set()
    jr._batcher.join(timeout=5)
    node.engine.journal = None  # post-mortem resolutions never reach the WAL


@pytest.mark.simnet
def test_drain_hands_off_to_healthy_peer(net, tmp_path):
    """Tentpole (b) on the cluster: a draining node ships its unstarted
    journaled jobs to a gossip-healthy ring peer over the existing TASK
    frame; the peer solves them; the drainer's WAL fully discharges —
    every accepted job was handed off or finished."""
    gate = threading.Event()
    entered = threading.Event()
    base = oracle_solve_fn()

    def gated(grids, geom, cfg):
        entered.set()
        gate.wait(600)
        return base(grids, geom, cfg)

    jr = Journal(str(tmp_path), fsync_interval_s=0.01)
    ea = SolverEngine(
        solve_fn=gated, batch_window_s=0.001, journal=jr
    ).start()
    a = sim_node(net, engine=ea)
    b = sim_node(net, anchor=a.addr)
    assert wait_until(
        net, lambda: len(a.network) == 2 and len(b.network) == 2, timeout=60
    ), "ring never formed"

    # One job in flight (held by the gate), three unstarted behind it —
    # submitted through the LOCAL path so none leave before the drain,
    # and only after the first solve has STARTED (batch window closed).
    j0 = a._submit_local(EASY, job_uuid="fly-0")
    assert entered.wait(30)
    queued = [
        a._submit_local(EASY, job_uuid=f"hand-{i}") for i in range(3)
    ]
    res = a.drain(timeout=0.1)
    assert res["state"] == "drained"
    assert res["handoffs"] == 3, res
    # Browning rode the gossip plane: peers stop affinity-routing here.
    if a.gossip is not None:
        assert a.gossip.view()[a.addr_s]["brown"] is True
    # The peer executes the handed-off TASKs (instant oracle solves).
    assert wait_until(
        net, lambda: b.engine.stats()["solved"] >= 3, timeout=120
    ), f"peer solved {b.engine.stats()['solved']}/3 handed-off jobs"
    # Handed-off entries discharged; the in-flight job finishes after the
    # gate opens — the WAL ends empty: nothing accepted was lost.
    gate.set()
    assert j0.wait(60) and j0.solved
    assert wait_until(
        net,
        lambda: (jr.sync_now() or True) and not jr.unresolved(),
        timeout=60,
    ), f"WAL entries stranded: {[e['uuid'] for e in jr.unresolved()]}"
    assert a.engine.metrics()["lifecycle"]["drain_handoffs"] == 3


@pytest.mark.simnet
def test_crash_restart_chaos_soak_zero_loss_bit_identical(net, tmp_path):
    """The acceptance soak: a 3-node ring under seeded drop/dup/delay
    chaos; the origin (journal-backed, its local solves gated so the
    crash catches real in-flight work) is killed mid-flight with its
    pending resolve buffer LOST, then reboots on the SAME address with
    the SAME journal directory, rejoins, and replays.  Every accepted
    job ends in a verdict bit-identical to the fault-free oracle; the
    WAL drains to empty — zero accepted-job loss."""
    wal_dir = str(tmp_path / "wal")
    boards = [EASY] + [np.asarray(h, np.int32) for h in HARD_9[:2]]
    expect = [solve_oracle(g, a_geom(g)) for g in boards]
    assert all(s is not None for s in expect)

    gate = threading.Event()
    base = oracle_solve_fn()

    def gated(grids, geom, cfg):
        gate.wait(600)
        return base(grids, geom, cfg)

    jr = Journal(wal_dir, fsync_interval_s=0.01)
    ea = SolverEngine(
        solve_fn=gated, batch_window_s=0.001, journal=jr
    ).start()
    a = sim_node(net, engine=ea)
    b = sim_node(net, anchor=a.addr)
    c = sim_node(net, anchor=a.addr)
    assert wait_until(
        net,
        lambda: all(len(n.network) == 3 for n in (a, b, c)),
        timeout=60,
    ), "ring never formed"

    # Ring formed cleanly; now the weather, then the work.
    net.set_schedule(
        FaultSchedule.seeded(seed=7, rate=0.05, kinds=("drop", "dup", "delay"))
    )
    uuids = [f"job-{i}" for i in range(9)]
    for i, u in enumerate(uuids):
        a.submit(boards[i % 3], job_uuid=u)
    # Let remote dispatches fly and some verdicts land (their WAL entries
    # discharge); a's own share stays gated in flight.
    net.advance(1.0)

    # CRASH: mid-flight, pending buffer lost, journal dir survives.
    addr = a.addr
    _crash(a, jr)
    gate.set()  # free the dead engine's device loop; journal already detached
    assert wait_until(
        net,
        lambda: addr_s(addr) not in b.network and addr_s(addr) not in c.network,
        timeout=240,
    ), "dead origin never evicted"

    # REBOOT: same address, same journal directory, fresh engine.
    jr2 = Journal(wal_dir, fsync_interval_s=0.01)
    ea2 = SolverEngine(
        solve_fn=oracle_solve_fn(), batch_window_s=0.001, journal=jr2
    ).start()
    a2 = ClusterNode(
        ea2, port=addr[1], anchor=b.addr, config=SIM,
        transport=net.transport(), clock=net.clock,
    ).start()
    net.nodes.append(a2)
    assert wait_until(
        net,
        lambda: all(len(n.network) == 3 for n in (a2, b, c)),
        timeout=240,
    ), "reborn origin never rejoined"

    replay = [ev["uuid"] for ev in jr2.unresolved()]
    assert replay, "crash caught no in-flight work — soak is vacuous"
    n = a2.recover()
    assert n == len(replay)
    assert ea2.metrics()["lifecycle"]["recovered_jobs"] == n

    # Every replayed job reaches a verdict bit-identical to the oracle.
    handles = {u: ea2._dup_job(u) for u in replay}
    assert all(h is not None for h in handles.values())
    assert wait_until(
        net,
        lambda: all(h.done.is_set() for h in handles.values()),
        timeout=240,
    ), f"replayed jobs stuck: {[u for u, h in handles.items() if not h.done.is_set()]}"
    for u, h in handles.items():
        i = int(u.split("-")[1])
        assert h.solved, f"replayed {u} ended unsolved: {h.error!r}"
        assert np.array_equal(h.solution, expect[i % 3]), (
            f"replayed {u} not bit-identical to the fault-free oracle"
        )
    # Zero loss: the WAL drains to empty once the replays discharge.
    assert wait_until(
        net,
        lambda: (jr2.sync_now() or True) and not jr2.unresolved(),
        timeout=60,
    ), f"WAL entries stranded: {[e['uuid'] for e in jr2.unresolved()]}"
    # The soak must actually have exercised the chaos plane.
    assert (
        net.counters["dropped"]
        + net.counters["duplicated"]
        + net.counters["delayed"]
    ) > 0, "seeded chaos never fired"
    ea2.stop(timeout=1)
    jr2.shutdown()


def addr_s(addr) -> str:
    return f"{addr[0]}:{addr[1]}"
