"""TPU-hardware regression lane (VERDICT r1 #8 / ROADMAP r1 #9).

Run with ``TPU_TESTS=1 python -m pytest tests -m tpu -q`` on a machine with
a real TPU attached.  The default (CPU-mesh) suite exercises the identical
Pallas kernel code in *interpret* mode; this lane compiles it through
Mosaic on hardware, so a lowering regression fails here instead of shipping
silently.  Every assertion is a bit-exactness check against the XLA
reference path computed on the same device.
"""

import numpy as np
import pytest

import jax

requires_tpu = pytest.mark.skipif(
    jax.default_backend() not in ("tpu", "axon"),
    reason="needs a real TPU backend (run with TPU_TESTS=1 on TPU hardware)",
)

pytestmark = [pytest.mark.tpu, requires_tpu]


def test_mosaic_sweep_matches_xla_on_device():
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import sweep_mosaic
    from distributed_sudoku_solver_tpu.ops.propagate import propagate_sweep

    rng = np.random.default_rng(7)
    cand = jnp.asarray(
        rng.integers(0, SUDOKU_9.full_mask + 1, size=(256, 9, 9), dtype=np.uint32)
    )
    ref = propagate_sweep(cand, SUDOKU_9)
    got = sweep_mosaic(cand, SUDOKU_9)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("rules", ["basic", "extended"])
def test_fixpoint_kernel_on_device(rules):
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.ops.pallas_propagate import (
        propagate_fixpoint_pallas,
    )
    from distributed_sudoku_solver_tpu.ops.propagate import propagate
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    grids = np.stack([EASY_9, *HARD_9] * 48)[:256].astype(np.int32)
    cand = encode_grid(jnp.asarray(grids), SUDOKU_9)
    ref, _ = propagate(cand, SUDOKU_9, rules=rules)
    got, _ = propagate_fixpoint_pallas(cand, SUDOKU_9, rules=rules)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("propagator", ["pallas", "slices"])
def test_solve_batch_propagators_on_device(propagator):
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    grids = jnp.asarray(np.stack(HARD_9).astype(np.int32))
    ref = solve_batch(grids, SUDOKU_9, SolverConfig(min_lanes=64, stack_slots=16))
    got = solve_batch(
        grids,
        SUDOKU_9,
        SolverConfig(min_lanes=64, stack_slots=16, propagator=propagator),
    )
    np.testing.assert_array_equal(np.asarray(ref.solved), np.asarray(got.solved))
    np.testing.assert_array_equal(np.asarray(ref.solution), np.asarray(got.solution))
    np.testing.assert_array_equal(np.asarray(ref.nodes), np.asarray(got.nodes))


def test_engine_flights_on_device():
    """The chunked flight loop end-to-end on hardware: solve, mid-flight
    snapshot, roots resume — the serving path the bench's p50 rides."""
    import time

    import numpy as np

    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    eng = SolverEngine(
        config=SolverConfig(min_lanes=64, stack_slots=32), max_batch=8
    ).start()
    try:
        jobs = [eng.submit(p) for p in (EASY_9, *HARD_9)]
        for j in jobs:
            assert j.wait(240)
            assert j.solved, j.error
            assert is_valid_solution(j.solution)
        # Roots-resume flight compiles and solves on hardware too.
        slow = SolverEngine(
            config=SolverConfig(min_lanes=8, stack_slots=16),
            chunk_steps=1,
            handicap_s=0.2,
        ).start()
        try:
            j = slow.submit(HARD_9[1])
            snap = None
            deadline = time.monotonic() + 120
            while snap is None and time.monotonic() < deadline:
                if j.done.is_set():
                    break
                snap = slow.snapshot_rows(j.uuid, timeout=10)
            assert j.wait(240)
            if snap is not None:
                jr = eng.submit_roots(snap[0], j.geom)
                assert jr.wait(240)
                assert jr.solved
                np.testing.assert_array_equal(jr.solution, j.solution)
        finally:
            slow.stop(timeout=2)
    finally:
        eng.stop(timeout=2)


def test_bulk_stepped_rungs_on_device():
    """Dispatch-time bounds on hardware: force stragglers through the
    stepped escalation rungs (the watchdog-fix path) and cross-check the
    default pipeline."""
    import numpy as np

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    grids = np.stack([EASY_9, *HARD_9]).astype(np.int32)
    ref = solve_bulk(grids, SUDOKU_9, BulkConfig(chunk=8))
    stepped = solve_bulk(
        grids,
        SUDOKU_9,
        BulkConfig(chunk=8, first_pass_steps=1, dispatch_steps=4),
    )
    np.testing.assert_array_equal(ref.solved, stepped.solved)
    np.testing.assert_array_equal(ref.solution, stepped.solution)
    assert stepped.solved.all()


def test_wire_roundtrip_on_device():
    """The bulk pipeline's packed wire format, end to end on hardware."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops import wire
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch_wire
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    grids = np.stack(HARD_9).astype(np.int32)
    packed = jnp.asarray(wire.pack_grids_host(grids, SUDOKU_9))
    out = solve_batch_wire(
        packed, SUDOKU_9, SolverConfig(min_lanes=len(grids), stack_slots=12)
    )
    sol, solved, unsat, _ = wire.unpack_result_host(np.asarray(out), SUDOKU_9)
    assert solved.all() and not unsat.any()
    for i in range(len(grids)):
        assert is_valid_solution(sol[i])


def test_fused_step_kernel_on_device():
    """The whole-round fused kernel (ops/pallas_step.py) compiles through
    Mosaic and matches the composite step's verdicts + solutions on a mixed
    corpus including a proven-unsat board."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    unsat = np.asarray(HARD_9[1]).copy()
    unsat[1, 6] = 8
    grids = jnp.asarray(np.stack([EASY_9, HARD_9[0], unsat]).astype(np.int32))
    ref = solve_batch(
        grids, SUDOKU_9, SolverConfig(min_lanes=128, stack_slots=16)
    )
    got = solve_batch(
        grids,
        SUDOKU_9,
        SolverConfig(min_lanes=128, stack_slots=16, step_impl="fused"),
    )
    np.testing.assert_array_equal(np.asarray(got.solved), np.asarray(ref.solved))
    np.testing.assert_array_equal(np.asarray(got.unsat), np.asarray(ref.unsat))
    np.testing.assert_array_equal(
        np.asarray(got.solution), np.asarray(ref.solution)
    )


def test_fused_engine_flight_on_device():
    """Fused configs serving engine flights on hardware (VERDICT r3 #1):
    the advance_frontier_fused chunk driver compiles through Mosaic and
    resolves jobs with oracle-valid solutions."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    eng = SolverEngine(
        config=SolverConfig(min_lanes=64, stack_slots=16, step_impl="fused"),
        max_batch=8,
    ).start()
    try:
        jobs = [eng.submit(p) for p in (EASY_9, *HARD_9)]
        for j in jobs:
            assert j.wait(240)
            assert j.solved, j.error
            assert is_valid_solution(j.solution)
    finally:
        eng.stop(timeout=2)


def test_fused_sharded_one_chip_mesh_on_device():
    """The fused kernel under shard_map on a 1-chip mesh (the only size
    this container offers): Mosaic inside shard_map compiles + solves."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.parallel import make_mesh
    from distributed_sudoku_solver_tpu.parallel.fused_sharded import (
        solve_batch_fused_sharded,
    )
    from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

    grids = np.stack([EASY_9, HARD_9[0]]).astype(np.int32)
    cfg = SolverConfig(
        min_lanes=128, stack_slots=16, max_steps=4096, step_impl="fused"
    )
    res = solve_batch_fused_sharded(
        jnp.asarray(grids), SUDOKU_9, cfg, mesh=make_mesh(jax.devices()[:1])
    )
    assert np.asarray(res.solved).all()
    for j in range(2):
        np.testing.assert_array_equal(
            np.asarray(res.solution[j]), solve_oracle(grids[j], SUDOKU_9)
        )


def test_fused_count_all_on_device():
    """In-kernel enumeration on hardware: the count-mode kernel (solved
    lanes pop and continue) compiles through Mosaic and produces exact
    model counts (288 4x4 grids; 62-solution 9x9)."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4, SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    res4 = solve_batch(
        jnp.asarray(np.zeros((1, 4, 4), np.int32)),
        SUDOKU_4,
        SolverConfig(
            min_lanes=32, stack_slots=64, max_steps=100_000,
            count_all=True, step_impl="fused",
        ),
    )
    assert int(res4.sol_count[0]) == 288
    assert bool(res4.unsat[0])

    few = np.asarray(EASY_9).copy()
    rng = np.random.default_rng(3)
    idx = np.flatnonzero(few.ravel())
    few.ravel()[rng.choice(idx, size=4, replace=False)] = 0
    res9 = solve_batch(
        jnp.asarray(few[None].astype(np.int32)),
        SUDOKU_9,
        SolverConfig(
            min_lanes=64, stack_slots=32, max_steps=100_000,
            count_all=True, step_impl="fused",
        ),
    )
    assert int(res9.sol_count[0]) == 62
    assert bool(res9.unsat[0])


def test_bulk_auto_picks_fused_at_16x16_on_device():
    """Round 4 widened the bulk auto-gate to any geometry whose tile fits
    (16x16 at S<=12): the auto path must compile and solve hexadoku with
    the fused first pass on hardware."""
    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
    from distributed_sudoku_solver_tpu.utils.puzzles import puzzle_batch

    g16 = geometry_for_size(16)
    boards = puzzle_batch(
        g16, 64, seed=9, n_clues=128, unique=False
    ).astype(np.int32)
    res = solve_bulk(boards, g16, BulkConfig(chunk=64))  # step_impl=None: auto
    assert res.solved.all()
    for i in range(0, 64, 16):
        assert is_valid_solution(res.solution[i], g16)


def test_fused_cover_kernel_on_device():
    """The exact-cover VMEM kernel (ops/pallas_cover.py) compiles through
    Mosaic on hardware and enumerates exactly: 8-queens = 92 (single-block
    row space) and pentomino 3x20 = 8 (multi-block streaming).  The
    precision trap this pins: f32 dots at default precision round the
    unpack matmuls' 16-bit words — these counts catch any regression."""
    import dataclasses

    from distributed_sudoku_solver_tpu.models.nqueens import nqueens_cover
    from distributed_sudoku_solver_tpu.models.pentomino import pentomino_cover
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_csp

    cfg = SolverConfig(
        min_lanes=128, stack_slots=32, max_steps=200_000,
        count_all=True, step_impl="fused",
    )
    q8 = nqueens_cover(8)
    res = solve_csp(q8.initial_state()[None], q8, cfg)
    assert int(res.sol_count[0]) == 92
    assert bool(res.unsat[0]) and not bool(res.overflowed[0])

    p = pentomino_cover(3, 20)
    assert p.w_rows > 32  # multi-block: exercises the blocked row passes
    res = solve_csp(
        p.initial_state()[None], p,
        dataclasses.replace(cfg, stack_slots=64),
    )
    assert int(res.sol_count[0]) == 8
    assert bool(res.unsat[0]) and not bool(res.overflowed[0])
