"""Fused VMEM cover kernel (``ops/pallas_cover.py``) vs the composite engine.

Mirrors the Sudoku fused-step suite's contract (``tests/test_fused_step.py``):
the fused path is a gated strategy — verdicts must be sound and counts
exact, while node accounting may differ at ``fused_steps`` granularity.
On the CPU test mesh the kernel runs in Pallas interpret mode (plain XLA
semantics); the hardware lanes live in ``tests/test_tpu.py`` and the
measured rows in ``benchmarks/bench_cover.py``.
"""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.cover import (
    build_cover,
    decode_sudoku_cover,
    sudoku_clue_rows,
    sudoku_cover,
)
from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.models.nqueens import (
    decode_queens,
    is_valid_queens,
    nqueens_cover,
)
from distributed_sudoku_solver_tpu.models.pentomino import (
    decode_tiling,
    is_valid_tiling,
    pentomino_cover,
)
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch, solve_csp

FUSED = SolverConfig(
    min_lanes=64, stack_slots=32, max_steps=40_000, step_impl="fused",
    fused_steps=4,
)
XLA = SolverConfig(min_lanes=64, stack_slots=32, max_steps=40_000)


def _roots(problem, n_jobs=1):
    return np.repeat(problem.initial_state()[None], n_jobs, axis=0)


@pytest.mark.parametrize("n", [4, 6, 8])
def test_fused_nqueens_solved_and_valid(n):
    p = nqueens_cover(n)
    res = solve_csp(_roots(p), p, FUSED)
    assert bool(res.solved[0])
    queens = decode_queens(p, np.asarray(res.solution[0]), n)
    assert is_valid_queens(queens, n)


@pytest.mark.parametrize("n", [2, 3])
def test_fused_nqueens_unsat_proven(n):
    p = nqueens_cover(n)
    res = solve_csp(_roots(p), p, FUSED)
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
    assert not bool(res.overflowed[0])


def test_fused_first_solution_matches_composite():
    """Identical branch heuristics => the first solution found agrees with
    the composite engine on a single-lane-per-job search."""
    import dataclasses

    p = nqueens_cover(7)
    one_lane = dict(lanes=1, min_lanes=1, steal=False)
    rf = solve_csp(
        _roots(p), p, dataclasses.replace(FUSED, **one_lane)
    )
    rx = solve_csp(_roots(p), p, dataclasses.replace(XLA, **one_lane))
    assert bool(rf.solved[0]) and bool(rx.solved[0])
    assert (
        p.chosen_rows(np.asarray(rf.solution[0])).tolist()
        == p.chosen_rows(np.asarray(rx.solution[0])).tolist()
    )


def test_fused_count_all_exact_nqueens():
    import dataclasses

    p = nqueens_cover(6)
    cfg = dataclasses.replace(FUSED, count_all=True)
    res = solve_csp(_roots(p), p, cfg)
    assert int(res.sol_count[0]) == 4  # OEIS A000170(6)
    assert bool(res.unsat[0])  # ran to exhaustion
    assert not bool(res.overflowed[0])


def test_fused_count_all_multi_block_pentomino():
    """A multi-block instance (w_rows > 32 words streams the row space in
    blocks) counts exactly: pentomino 3x20 has 8 tilings (2 classic x 4
    rectangle symmetries)."""
    import dataclasses

    p = pentomino_cover(3, 20)
    assert p.w_rows > 32  # the point of the test: multi-block streaming
    cfg = dataclasses.replace(
        FUSED, min_lanes=128, stack_slots=64, max_steps=200_000,
        count_all=True,
    )
    res = solve_csp(_roots(p), p, cfg)
    rx = solve_csp(
        _roots(p), p,
        dataclasses.replace(
            XLA, min_lanes=128, stack_slots=64, max_steps=200_000,
            count_all=True,
        ),
    )
    assert int(res.sol_count[0]) == int(rx.sol_count[0]) == 8
    assert bool(res.unsat[0]) and not bool(res.overflowed[0])


def test_fused_pentomino_tiling_valid():
    import dataclasses

    p = pentomino_cover(5, 12)
    cfg = dataclasses.replace(
        FUSED, min_lanes=128, stack_slots=64, max_steps=200_000
    )
    res = solve_csp(_roots(p), p, cfg)
    assert bool(res.solved[0])
    assert is_valid_tiling(decode_tiling(p, np.asarray(res.solution[0]), 5, 12))


def test_fused_overflow_downgrades_not_wrong():
    """A stack too shallow for the search must flag overflow (count is a
    lower bound), never report a wrong verdict."""
    import dataclasses

    p = nqueens_cover(8)
    cfg = dataclasses.replace(
        FUSED, lanes=1, min_lanes=1, stack_slots=2, steal=False,
        count_all=True,
    )
    res = solve_csp(_roots(p), p, cfg)
    assert bool(res.overflowed[0])
    # A 2-slot stack on one lane drops most of the 8-queens tree: the
    # count must come back as a strict lower bound, never inflated.
    assert 0 <= int(res.sol_count[0]) < 92


def test_fused_sudoku_cover_matches_native_kernel():
    """Sudoku-as-cover through the fused cover kernel agrees with the
    native Sudoku kernels — two independent engines, one answer."""
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    p = sudoku_cover(SUDOKU_9)
    root = p.state_with_rows_taken(sudoku_clue_rows(EASY_9))[None]
    res = solve_csp(root, p, FUSED)
    assert bool(res.solved[0])
    via_cover = decode_sudoku_cover(p, np.asarray(res.solution[0]), 9)
    native = solve_batch(np.asarray(EASY_9, np.int32)[None], SUDOKU_9, XLA)
    assert np.array_equal(via_cover, np.asarray(native.solution[0]))


def test_fused_rejects_non_cover_csp():
    from distributed_sudoku_solver_tpu.ops.solve import sudoku_csp

    csp = sudoku_csp(SUDOKU_9, XLA)
    with pytest.raises(ValueError, match="exact-cover"):
        solve_csp(
            np.zeros((1, 9, 9), np.uint32), csp,
            SolverConfig(min_lanes=16, step_impl="fused"),
        )


def test_incidence_distinguishes_digest():
    """Instances differing only in secondary columns must trace distinctly
    (the fused kernel bakes the full incidence into the program)."""
    a = np.zeros((4, 3), bool)
    a[:, 0] = True
    a[0, 2] = a[1, 2] = True  # secondary column shared by rows 0, 1
    b = a.copy()
    b[2, 2] = True
    pa = build_cover("d", a, 1)
    pb = build_cover("d", b, 1)
    assert pa != pb


def test_legacy_instances_without_incidence_raise_cleanly():
    from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP
    from distributed_sudoku_solver_tpu.ops.pallas_cover import cover_consts

    p = nqueens_cover(4)
    legacy = ExactCoverCSP(
        name=p.name, n_rows=p.n_rows, n_primary=p.n_primary,
        col_rows=p.col_rows, row_cols=p.row_cols, elim=p.elim,
    )
    with pytest.raises(ValueError, match="incidence"):
        cover_consts(legacy)


def test_fused_cover_sharded_on_mesh():
    """The cover kernel under shard_map on the 8-device mesh: find-one
    solves with a valid decode, and count_all psums disjoint per-chip
    subtree counts to the exact total (6-queens: 4)."""
    import dataclasses

    from distributed_sudoku_solver_tpu.parallel import (
        make_mesh,
        solve_csp_fused_sharded,
        solve_csp_sharded,
    )

    p = nqueens_cover(6)
    mesh = make_mesh()
    cfg = dataclasses.replace(FUSED, min_lanes=8 * 16)
    res = solve_csp_fused_sharded(_roots(p), p, cfg, mesh=mesh)
    assert bool(res.solved[0])
    queens = decode_queens(p, np.asarray(res.solution[0]), 6)
    assert is_valid_queens(queens, 6)

    cnt_cfg = dataclasses.replace(cfg, count_all=True)
    rf = solve_csp_fused_sharded(_roots(p), p, cnt_cfg, mesh=mesh)
    rx = solve_csp_sharded(
        _roots(p), p,
        dataclasses.replace(XLA, min_lanes=8 * 16, count_all=True),
        mesh=mesh,
    )
    assert int(rf.sol_count[0]) == int(rx.sol_count[0]) == 4
    assert bool(rf.unsat[0]) and not bool(rf.overflowed[0])
