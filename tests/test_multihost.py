"""Multi-process control plane (VERDICT r1 #7): two OS processes, each with
its own ``jax.distributed`` runtime, form the ring over real TCP, dispatch
jobs across the process boundary, and survive a hard kill — closing the
round-1 "loopback threads only" caveat.  The reference's own deployment
model was multiple OS processes (SURVEY.md §4); this automates it.
"""

import json
import os
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cluster_with_jax_distributed(tmp_path):
    coord, p0, p1 = _free_port(), _free_port(), _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel in child processes
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.join(os.path.dirname(__file__), "multihost_script.py")
    args = [sys.executable, script]
    tail = [str(coord), str(p0), str(p1), str(tmp_path)]
    procs = [
        subprocess.Popen(
            [*args, str(role), *tail],
            env=env,
            cwd=repo_root,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for role in (0, 1)
    ]
    try:
        out0, _ = procs[0].communicate(timeout=240)
        # role 1 dies by design (os._exit(9)) — only reap it.
        out1, _ = procs[1].communicate(timeout=30)
        debug = (
            f"--- role0 ---\n{out0.decode(errors='replace')[-3000:]}\n"
            f"--- role1 ---\n{out1.decode(errors='replace')[-3000:]}"
        )
        assert procs[0].returncode == 0, debug

        with open(tmp_path / "result0.json") as f:
            res = json.load(f)
        assert res["process_count"] == 2
        assert res["ring_formed"], debug
        assert res["all_solved"]
        assert res["peer_validations"] > 0, "no job ran on the peer process"
        assert res["peer_removed"], "dead peer never evicted from the view"
        assert res["post_kill_solved"]
        with open(tmp_path / "result1.json") as f:
            assert json.load(f)["joined"]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
