"""Multi-process control plane (VERDICT r1 #7): two OS processes, each with
its own ``jax.distributed`` runtime, form the ring over real TCP, dispatch
jobs across the process boundary, and survive a hard kill — closing the
round-1 "loopback threads only" caveat.  The reference's own deployment
model was multiple OS processes (SURVEY.md §4); this automates it.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from multimesh_script import free_port as _free_port  # noqa: E402


# Capability gate for cross-process SPMD: the XLA:CPU PjRt client has no
# multi-process runtime — a 2-process ``jax.distributed`` mesh fails inside
# the child controllers with "Multiprocess computations aren't implemented
# on the CPU backend" (pre-existing container limitation, CHANGES.md PR 3).
# TPU (and GPU) clients implement it; the rest of this module's
# control-plane tests ride plain TCP and stay on.  The gate mirrors
# conftest's lane switch via the env var INSTEAD of asking jax (outside the
# TPU lane conftest forces the CPU backend anyway, and calling
# jax.default_backend() here would initialize the hardware backend in the
# pytest parent at collection time — poisoning the very child controllers
# the un-skipped test spawns).
_TPU_LANE = os.environ.get("TPU_TESTS") == "1"


@pytest.mark.skipif(
    not _TPU_LANE,
    reason="needs a backend with cross-process SPMD support (XLA:CPU PjRt "
    "has no multi-process runtime: \"Multiprocess computations aren't "
    "implemented\"); run the TPU lane (TPU_TESTS=1) to exercise this",
)
def test_cross_process_mesh(tmp_path):
    """VERDICT r2 #3: ONE device mesh spanning two OS processes.

    Two controller processes x 4 virtual CPU devices each join one
    ``jax.distributed`` runtime and run ``solve_batch_sharded`` over the
    global 8-device mesh — ``shard_map`` collectives (psum/pmin/ppermute
    ring steals) cross the process boundary.  The result must be
    bit-identical (solutions AND node counts AND step count) to this
    parent process's own single-process 8-device run of the same program:
    the process boundary must be invisible to the math.
    """
    import numpy as np

    from multimesh_script import spawn_mesh_pair

    pair = spawn_mesh_pair(tmp_path, devices_per_proc=4)
    debug = "".join(
        f"--- role{i} (rc={rc}) ---\n{out[-3000:]}\n"
        for i, (rc, out) in enumerate(pair)
    )
    assert all(rc == 0 for rc, _ in pair), debug

    results = []
    for role in (0, 1):
        with open(tmp_path / f"mesh_result{role}.json") as f:
            results.append(json.load(f))
    for r in results:
        assert r["process_count"] == 2, debug
        assert r["global_devices"] == 8 and r["local_devices"] == 4
        assert r["mesh_spans_processes"], "mesh did not span both processes"

    # Single-process 8-device reference (this pytest process's mesh).
    import jax

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.parallel.mesh import make_mesh
    from distributed_sudoku_solver_tpu.parallel.sharded import (
        solve_batch_sharded,
    )
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    grids = np.stack([np.asarray(b) for b in HARD_9[:4]]).astype(np.int32)
    cfg = SolverConfig(min_lanes=32, stack_slots=32, max_steps=4096)
    ref = solve_batch_sharded(grids, SUDOKU_9, cfg, mesh=make_mesh(jax.devices()))

    for r in results:
        assert r["solved"] == np.asarray(ref.solved).tolist()
        assert r["solution"] == np.asarray(ref.solution).tolist()
        assert r["nodes"] == np.asarray(ref.nodes).tolist()
        assert r["steps"] == int(np.asarray(ref.steps))
    # Both controllers saw the identical replicated result.
    assert results[0] == results[1]


def test_two_process_cluster_with_jax_distributed(tmp_path):
    coord, p0, p1 = _free_port(), _free_port(), _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel in child processes
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.join(os.path.dirname(__file__), "multihost_script.py")
    args = [sys.executable, script]
    tail = [str(coord), str(p0), str(p1), str(tmp_path)]
    procs = [
        subprocess.Popen(
            [*args, str(role), *tail],
            env=env,
            cwd=repo_root,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for role in (0, 1)
    ]
    try:
        out0, _ = procs[0].communicate(timeout=240)
        # role 1 dies by design (os._exit(9)) — only reap it.
        out1, _ = procs[1].communicate(timeout=30)
        debug = (
            f"--- role0 ---\n{out0.decode(errors='replace')[-3000:]}\n"
            f"--- role1 ---\n{out1.decode(errors='replace')[-3000:]}"
        )
        assert procs[0].returncode == 0, debug

        with open(tmp_path / "result0.json") as f:
            res = json.load(f)
        assert res["process_count"] == 2
        assert res["ring_formed"], debug
        assert res["all_solved"]
        assert res["peer_validations"] > 0, "no job ran on the peer process"
        assert res["peer_removed"], "dead peer never evicted from the view"
        assert res["post_kill_solved"]
        with open(tmp_path / "result1.json") as f:
            assert json.load(f)["joined"]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
