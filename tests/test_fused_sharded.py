"""Lane-sharded fused solve on the virtual 8-device CPU mesh (VERDICT r3 #2).

The fused kernel runs per chip (Pallas interpret mode here; the TPU lane
compiles it natively) with the ring collectives of `parallel/sharded.py`
around it.  Mirrors `tests/test_sharded.py`: verdict agreement with the
single-device paths, ring-steal occupancy, unsat proofs, submesh sizes.
"""

import jax
import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.parallel import (
    make_mesh,
    solve_batch_fused_sharded,
    solve_batch_sharded,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9


def _cfg(**kw):
    kw.setdefault("min_lanes", 16)
    kw.setdefault("stack_slots", 16)
    kw.setdefault("max_steps", 4096)
    return SolverConfig(step_impl="fused", **kw)


def _unsat_board():
    bad = np.asarray(EASY_9).copy()
    bad[0, 0] = bad[0, 1] = 5
    return bad


def test_fused_sharded_matches_single_device():
    grids = np.stack([EASY_9, *HARD_9])
    res1 = solve_batch(grids, SUDOKU_9, _cfg())
    res8 = solve_batch_fused_sharded(grids, SUDOKU_9, _cfg(), mesh=make_mesh())
    assert np.all(np.asarray(res8.solved))
    assert not np.any(np.asarray(res8.overflowed))
    np.testing.assert_array_equal(np.asarray(res8.solved), np.asarray(res1.solved))
    for j in range(grids.shape[0]):
        sol = np.asarray(res8.solution[j])
        assert is_valid_solution(sol)
        np.testing.assert_array_equal(sol, solve_oracle(grids[j], SUDOKU_9))


def test_fused_sharded_via_dispatch():
    """solve_batch_sharded with a fused config routes to the fused driver
    (one dispatch site) and agrees with the composite sharded path."""
    grids = np.stack([EASY_9, HARD_9[0]])
    ref = solve_batch_sharded(grids, SUDOKU_9, SolverConfig(min_lanes=16))
    got = solve_batch_sharded(grids, SUDOKU_9, _cfg())
    np.testing.assert_array_equal(np.asarray(got.solved), np.asarray(ref.solved))
    np.testing.assert_array_equal(
        np.asarray(got.solution), np.asarray(ref.solution)
    )


def test_fused_ring_steal_spreads_one_hard_job():
    # One job, 8 chips: only the cross-chip ring ppermute can occupy the
    # other 7 chips' lanes (HARD_9[0] needs ~70 branch nodes).
    grids = np.asarray(HARD_9[0])[None]
    cfg = _cfg(min_lanes=32, stack_slots=32, ring_steal_k=4, fused_steps=2)
    res = solve_batch_fused_sharded(grids, SUDOKU_9, cfg)
    assert bool(res.solved[0])
    assert int(res.steals) > 0, "no cross-chip (or local) steal ever happened"
    assert is_valid_solution(np.asarray(res.solution[0]))


def test_fused_sharded_unsat_is_proven():
    res = solve_batch_fused_sharded(_unsat_board()[None], SUDOKU_9, _cfg())
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
    assert not bool(res.overflowed[0])
    assert int(res.sol_count[0]) == 0


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_fused_submesh_sizes(n_dev):
    mesh = make_mesh(jax.devices()[:n_dev])
    grids = np.stack([EASY_9, HARD_9[0]])
    res = solve_batch_fused_sharded(grids, SUDOKU_9, _cfg(), mesh=mesh)
    assert np.all(np.asarray(res.solved))
    assert np.all(np.asarray(res.sol_count) == 1)
    for j in range(2):
        assert is_valid_solution(np.asarray(res.solution[j]))


def test_bulk_mesh_accepts_fused():
    """ops/bulk with a mesh + explicit fused runs the sharded fused driver
    end-to-end (auto mode only picks fused on TPU)."""
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk

    boards = np.stack([EASY_9, HARD_9[0], _unsat_board(), HARD_9[2]]).astype(
        np.int32
    )
    ref = solve_bulk(
        boards, SUDOKU_9, BulkConfig(chunk=8, stack_slots=16, step_impl="xla"),
        mesh=make_mesh(),
    )
    got = solve_bulk(
        boards, SUDOKU_9, BulkConfig(chunk=8, stack_slots=16, step_impl="fused"),
        mesh=make_mesh(),
    )
    assert (got.solved == ref.solved).all()
    assert (got.unsat == ref.unsat).all()
    assert (got.solution == ref.solution).all()


def test_fused_sharded_rejects_generic_csp():
    from distributed_sudoku_solver_tpu.models.cover import build_cover
    from distributed_sudoku_solver_tpu.parallel import solve_csp_sharded

    problem = build_cover("eye4", np.eye(4, dtype=bool), n_primary=4)
    states0 = problem.initial_state()[None]
    with pytest.raises(ValueError, match="Sudoku"):
        solve_csp_sharded(states0, problem, _cfg())
