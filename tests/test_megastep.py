"""Round-19 serving megastep (``serving/megastep.py``): latency-mode
flights that fuse N advance chunks into ONE donated dispatch with an
in-graph early exit, one host sync per flight.

Four lanes:

* **bit-identity** — the megastep's verdict (solved/unsat, the decoded
  solution grid, sol_count) is bit-identical to the chunked path's on
  the hard corpus, for both step implementations.  The in-graph loop
  changes WHEN the host looks, never what the search computes.
* **degrade-to-chunked** (round-9 taxonomy) — budget exhaustion, device
  faults, and breaker deflection all return the job to the chunked
  paths unharmed; every degrade is counted by cause and the job still
  solves.
* **routing contract** — latency is an opt-in: per-request ``latency=``
  overrides the engine default in both directions, and an unfit gang
  shape (``resident_solver_config`` misfit) is counted once and
  bypassed forever, never an error.
* **accounting** — the flight's single sync lands in
  ``frontdoor_megastep_ms`` ONLY: the per-chunk ``chunk_wall_ms``/
  ``sync_wall_ms`` seams and the ``rpc_floor`` estimator stay empty on
  an engine that only flew megasteps (the round-19 double-count sweep).

The one-sync-per-flight fetch-count guard itself lives in
``tests/test_status_pipeline.py`` (the megastep lane), beside the
per-chunk guards it extends.
"""

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.megastep import MegastepConfig
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
FUSED_SMALL = SolverConfig(
    min_lanes=8, stack_slots=16, step_impl="fused", fused_steps=2
)
MS = MegastepConfig(gang_lanes=8, chunk_steps=16, max_chunks=64)


def _solve_chunked(cfg, boards):
    """The chunked baseline: the same boards through a resident-flight
    engine (no megastep installed at all).  The resident collect path is
    the megastep's verdict twin — the same ``_verdict_jit`` payload, the
    same ``sol_count`` contract (exactly 1 for a solved job in normal
    mode; the static finalize path predates that contract and may report
    0 for a job purged at its solve chunk)."""
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    rc = ResidentConfig(
        job_slots=4, gang_lanes=4, queue_depth=32, attach_batch=4,
        chunk_steps=16,
    )
    eng = SolverEngine(config=cfg, max_batch=8, resident=rc).start()
    try:
        jobs = [eng.submit(np.asarray(b, np.int32)) for b in boards]
        for j in jobs:
            assert j.wait(240), j.error
    finally:
        eng.stop(timeout=2)
    return jobs


# -- bit-identity -------------------------------------------------------------


@pytest.mark.parametrize("cfg", [SMALL, FUSED_SMALL], ids=["xla", "fused"])
def test_verdict_bit_identical_to_chunked(cfg):
    boards = [np.asarray(b) for b in HARD_9] + [np.asarray(EASY_9)]
    base = _solve_chunked(cfg, boards)
    eng = SolverEngine(
        config=cfg, max_batch=8, latency_mode=True, megastep=MS
    ).start()
    try:
        for b, ref in zip(boards, base):
            j = eng.submit(np.asarray(b, np.int32))
            assert j.wait(240), j.error
            assert j.solved == ref.solved and j.unsat == ref.unsat
            np.testing.assert_array_equal(
                np.asarray(j.solution), np.asarray(ref.solution)
            )
            assert j.sol_count == ref.sol_count
        mf = eng._megasteps[SUDOKU_9]
        m = mf.metrics()
        # Every board flew; none degraded to the chunked path.
        assert m["flights"] == len(boards) and m["solved"] == len(boards)
        assert all(v == 0 for v in m["degraded"].values())
    finally:
        eng.stop(timeout=2)


def test_unsat_board_proven_on_the_megastep():
    bad = np.zeros((9, 9), np.int32)
    bad[0, 0] = bad[0, 1] = 5
    eng = SolverEngine(
        config=SMALL, max_batch=8, latency_mode=True, megastep=MS
    ).start()
    try:
        j = eng.submit(bad)
        assert j.wait(120)
        assert j.unsat and j.exhausted and not j.solved
        m = eng._megasteps[SUDOKU_9].metrics()
        # A complete proof (all-dead early exit), not a shed/degrade.
        assert m["unsat"] == 1 and m["flights"] == 1
        assert all(v == 0 for v in m["degraded"].values())
    finally:
        eng.stop(timeout=2)


# -- degrade-to-chunked (round-9 taxonomy) ------------------------------------


def test_budget_exhaustion_degrades_to_chunked():
    """A flight that exhausts max_chunks with work left returns False and
    the CHUNKED path (which has no step budget) finishes the job; the
    degrade is counted under its cause."""
    tiny = MegastepConfig(gang_lanes=8, chunk_steps=1, max_chunks=1)
    eng = SolverEngine(
        config=SMALL, max_batch=8, latency_mode=True, megastep=tiny
    ).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        m = eng._megasteps[SUDOKU_9].metrics()
        assert m["degraded"]["budget"] == 1
        assert m["flights"] == 1 and m["solved"] == 0  # flew, didn't finish
    finally:
        eng.stop(timeout=2)


def test_fault_degrades_and_breaker_deflects():
    """A device fault mid-flight degrades the job to the chunked path
    (counted under 'fault', mailbox rebuilt); consecutive failures trip
    the flight's circuit breaker, after which latency submits deflect in
    O(1) WITHOUT touching the device — and every job still solves."""
    inj = faults.FaultInjector(
        faults.FaultSchedule.at(
            {"megastep.advance": {0: "preempt", 1: "preempt"}}
        )
    )
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        latency_mode=True,
        megastep=MS,
        recovery=faults.RecoveryPolicy(
            breaker_failures=2, breaker_cooldown_s=600.0
        ),
    ).start()
    try:
        with faults.injected(inj):
            jobs = [eng.submit(HARD_9[i % 3]) for i in range(3)]
            for j in jobs:
                assert j.wait(120) and j.solved, j.error
        m = eng._megasteps[SUDOKU_9].metrics()
        assert m["degraded"]["fault"] == 2
        assert m["degraded"]["breaker"] == 1
        assert m["flights"] == 0  # no flight ever completed
        assert m["breaker"]["state"] == "open"
        # The chunked fallback pays its own seams (engine.launch /
        # engine.advance / fetch.*); the MEGASTEP seam saw exactly the
        # two faulted flights — the deflected submit never reached it.
        assert inj.metrics()["dispatches"].get("megastep.advance") == 2
    finally:
        eng.stop(timeout=2)


# -- routing contract ---------------------------------------------------------


def test_per_request_latency_overrides_engine_default():
    # Engine default OFF, per-request opt-IN:
    eng = SolverEngine(config=SMALL, max_batch=8, megastep=MS).start()
    try:
        j1 = eng.submit(HARD_9[0], latency=True)
        assert j1.wait(120) and j1.solved, j1.error
        assert eng._megasteps[SUDOKU_9].flights == 1
        j2 = eng.submit(HARD_9[1])  # default: the chunked path
        assert j2.wait(120) and j2.solved, j2.error
        assert eng._megasteps[SUDOKU_9].flights == 1
    finally:
        eng.stop(timeout=2)
    # Engine default ON, per-request opt-OUT:
    eng = SolverEngine(
        config=SMALL, max_batch=8, latency_mode=True, megastep=MS
    ).start()
    try:
        j = eng.submit(HARD_9[0], latency=False)
        assert j.wait(120) and j.solved, j.error
        assert SUDOKU_9 not in eng._megasteps  # never even built
    finally:
        eng.stop(timeout=2)


def test_unfit_gang_shape_counted_once_and_bypassed(monkeypatch):
    """A geometry the megastep gang cannot serve (resident_solver_config
    misfit) is counted ONCE, cached as unservable, and every latency
    submit falls through to the chunked path — never an error."""
    import distributed_sudoku_solver_tpu.serving.megastep as megastep_mod

    def misfit(base, geom, rcfg):
        raise ValueError("forced gang-shape misfit")

    monkeypatch.setattr(megastep_mod, "resident_solver_config", misfit)
    eng = SolverEngine(
        config=SMALL, max_batch=8, latency_mode=True, megastep=MS
    ).start()
    try:
        jobs = [eng.submit(HARD_9[0]), eng.submit(HARD_9[1])]
        for j in jobs:
            assert j.wait(120) and j.solved, j.error
        m = eng.metrics()
        assert m["megastep_unfit"] == 1  # cached: not re-counted per submit
        assert "megastep" not in m  # no live flight section
    finally:
        eng.stop(timeout=2)


# -- accounting: the single sync lands in ONE place ---------------------------


def test_single_sync_never_double_counted():
    """The megastep's one fetch is recorded whole-flight in
    frontdoor_megastep_ms and NOWHERE else: the per-chunk chunk/sync
    walls and the rpc_floor estimator (whose samples mean 'one chunk's
    sync' / 'one floor') stay empty on an engine that only flew
    megasteps."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, latency_mode=True, megastep=MS
    ).start()
    try:
        for b in (HARD_9[0], EASY_9):
            j = eng.submit(np.asarray(b, np.int32))
            assert j.wait(120) and j.solved, j.error
        m = eng.metrics()
        ms = m["megastep"]["9x9"]
        assert ms["flights"] == 2 and ms["solved"] == 2
        assert ms["chunks_per_flight"] >= 1
        assert ms["flight_wall_ms"]["count"] == 2
        assert sum(m["hist"]["frontdoor_megastep_ms"]["counts"]) == 2
        # The round-19 double-count sweep: nothing leaked into the
        # per-chunk seams or the floor estimator.
        assert not eng.chunk_wall.snapshot()
        assert not eng.sync_wall.snapshot()
        # The hist section drops empty families: the per-chunk seams
        # must simply be absent on a megastep-only engine.
        assert "chunk_wall_ms" not in m["hist"]
        assert "sync_wall_ms" not in m["hist"]
        assert "rpc_floor_ms" not in m
    finally:
        eng.stop(timeout=2)
