"""Trace-replay capacity planner (benchmarks/replay.py, ISSUE 15): the
deterministic simnet lane — byte-identical seeded artifacts, the overload
soak that walks the brownout ladder 1 -> 2 -> 3 and back to 0 with zero
lost jobs, capacity scaling, and the regress.py dsst-replay/1 rules.

The workload fixtures are hand-built ``dsst-workload/1`` docs (the exact
shape ``bench_poisson --workload-out`` records — pinned against the
recorder by the slow-lane integration test at the bottom), so the fast
lane never pays an engine boot.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from benchmarks import regress
from benchmarks.replay import SCHEMA, WORKLOAD_SCHEMA, replay
from distributed_sudoku_solver_tpu.serving import brownout

BENCH_PARAMS = {
    "jobs": 80, "mean_gap_ms": 50.0, "handicap_ms": 50.0,
    "chunk_steps": 8, "seed": 7,
}


def _workload(n=80, device_every=4, device_wall_ms=2000.0,
              easy_wall_ms=5.0, gap_ms=50.0, slots=2, queue_depth=8):
    """Synthetic trace: easy native traffic with a device job every
    ``device_every`` arrivals — the overload dial is the ratio of device
    service time to slots x gap."""
    jobs = []
    t = 0.0
    for i in range(n):
        if i % device_every == 0:
            jobs.append({
                "offset_ms": round(t, 3), "tier": "hard", "board": [[0]],
                "route": "device", "wall_ms": device_wall_ms,
                "solved": True, "unsat": False,
            })
        else:
            jobs.append({
                "offset_ms": round(t, 3), "tier": "easy", "board": [[0]],
                "route": "native", "wall_ms": easy_wall_ms,
                "solved": True, "unsat": False,
            })
        t += gap_ms
    return {
        "schema": WORKLOAD_SCHEMA,
        "params": dict(BENCH_PARAMS, jobs=n),
        "engine": "resident",
        "job_slots": slots,
        "queue_depth": queue_depth,
        "jobs_trace": jobs,
    }


@pytest.mark.simnet
def test_two_seeded_replays_are_byte_identical():
    """The determinism pin (ISSUE 15 satellite): same trace, same seed,
    same knobs -> byte-identical artifacts, including the brownout
    stage walk and shed accounting."""
    wl = _workload()
    a1 = replay(wl, nodes=1, seed=3)
    a2 = replay(wl, nodes=1, seed=3)
    assert json.dumps(a1, sort_keys=True) == json.dumps(a2, sort_keys=True)
    assert a1["schema"] == SCHEMA


@pytest.mark.simnet
def test_overload_soak_walks_ladder_and_loses_nothing():
    """The acceptance soak: a seeded overload drives the controller
    through stage 1 -> 2 -> 3 and back to 0, zero jobs lost overall
    (completed + shed == offered; shed jobs carry honest 429/503
    statuses, never silent drops), transitions exactly-once counted."""
    wl = _workload()
    art = replay(wl, nodes=1, seed=3)
    # The ladder climbed to the top and recovered through the cooldown.
    assert art["max_stage"] == 3 and art["brownout_engaged"]
    assert art["final_stages"] == [0]
    # One full cycle: 3 escalations + 3 de-escalations, exactly once.
    assert art["transitions"] == 6
    # Zero lost: every offered job either completed or was shed honestly.
    assert art["completed"] + art["shed"]["total"] == art["jobs"]
    assert art["shed"]["total"] > 0
    assert set(art["shed"]["by_status"]) <= {"503", "429"}
    assert sum(art["shed"]["by_status"].values()) == art["shed"]["total"]
    # Stage 2 shed easy-tier 503s before stage 3's 429s — the
    # value-ordered ladder, not random drops.
    assert art["shed"]["by_tier"].get("easy", 0) > 0
    # Residency covers the whole virtual run, stages > 0 included.
    assert sum(art["stage_residency_s"][1:]) > 0


@pytest.mark.simnet
def test_ample_capacity_never_engages_brownout():
    """The capacity question inverted: enough slots -> the same traffic
    replays without the controller ever leaving stage 0, and predicted
    walls equal the recorded walls exactly (service model = recorded
    wall, uncontended)."""
    wl = _workload(slots=64)
    art = replay(wl, nodes=1, seed=3)
    assert not art["brownout_engaged"] and art["max_stage"] == 0
    assert art["shed"]["total"] == 0
    assert art["completed"] == art["jobs"]
    assert art["transitions"] == 0
    # Uncontended replay reproduces the trace bit-for-bit.
    assert art["tiers"]["hard"]["p95_ms"] == 2000.0
    assert art["tiers"]["easy"]["p95_ms"] == 5.0


@pytest.mark.simnet
def test_fleet_scaling_relieves_the_single_node():
    """The capacity experiment this harness exists for: the overloaded
    1-node replay sheds; the same trace over a 4-node fleet (least-
    outstanding routing) sheds nothing."""
    wl = _workload()
    one = replay(wl, nodes=1, seed=3)
    four = replay(wl, nodes=4, seed=3)
    assert one["shed"]["total"] > 0
    assert four["shed"]["total"] == 0
    assert four["completed"] == four["jobs"]
    assert four["params"]["nodes"] == 4


@pytest.mark.simnet
def test_bounded_queue_answers_saturation_429():
    """The model's admission queue is really bounded (review finding):
    device jobs beyond slots + queue_depth are refused with the
    saturation 429 — they never 'complete' with queueing walls real
    clients would have been 429'd before paying."""
    wl = _workload(n=16, device_every=1, device_wall_ms=5000.0,
                   gap_ms=10.0, slots=1, queue_depth=2)
    art = replay(
        wl, nodes=1, seed=0,
        # Generous SLO: every refusal below must be SATURATION, not a
        # brownout stage shed.
        slo_spec="solve_p95_ms<=600000,error_rate<=0.5",
    )
    assert art["completed"] + art["shed"]["total"] == art["jobs"]
    assert art["shed"]["by_tier"].get("saturated", 0) > 0
    assert art["shed"]["by_status"] == {"429": art["shed"]["total"]}
    # slots(1) + queue(2) in service/waiting at the burst peak; the rest
    # of the burst refused.
    assert art["completed"] < art["jobs"]


@pytest.mark.simnet
def test_gate_tier_uses_recorded_tier_not_final_route():
    """An easy-generated board whose device shadow won the recorded race
    (tier='easy', route='device') is still probe-easy: at stage 2 the
    replay sheds it with 503 instead of admitting it to a device slot
    (review finding)."""
    wl = _workload()  # drives the single node to stage 2+ mid-traffic
    for j in wl["jobs_trace"]:
        if j["route"] == "device":
            j["tier"] = "easy"  # the shadow-won-the-race shape
    art = replay(wl, nodes=1, seed=3)
    assert art["max_stage"] >= 2
    # Every brownout shed is easy-tier now (the only hard candidates are
    # gone), and stage-2 503s exist — route='device' did not smuggle the
    # easy boards past the easy-tier gate.
    assert art["shed"]["by_tier"].get("hard", 0) == 0
    assert art["shed"]["by_tier"].get("easy", 0) > 0
    assert art["shed"]["by_status"].get("503", 0) > 0


# -- regress.py dsst-replay/1 rules --------------------------------------------


def _live_artifact(tiers=None, resident_p95=2000.0, params=None):
    doc = {
        "schema": regress.SCHEMA,
        "params": dict(params if params is not None else BENCH_PARAMS),
        "static": {"p50_ms": 1.0, "p95_ms": 2.0},
        "resident": {"p50_ms": 1.0, "p95_ms": resident_p95},
    }
    if tiers is not None:
        doc["resident"]["tiers"] = tiers
    return doc


def _replay_artifact(tiers, workload_params=None, nodes=1, rate_x=1.0,
                     shed_total=0):
    return {
        "schema": SCHEMA,
        "params": {
            "workload": dict(
                workload_params if workload_params is not None
                else BENCH_PARAMS
            ),
            "nodes": nodes, "slots": 8, "queue_depth": 64,
            "rate_x": rate_x, "seed": 0,
            "slo": "solve_p95_ms<=2000,error_rate<=0.01",
            "brownout": {"enter": 1.0, "exit": 0.5, "quiet_s": 5.0},
        },
        "jobs": 48, "completed": 48 - shed_total,
        "shed": {"total": shed_total, "by_tier": {}, "by_status": {}},
        "overall": {"p50_ms": 10.0, "p95_ms": 1900.0},
        "tiers": tiers,
        "routes": {},
        "stage_residency_s": [100.0, 0.0, 0.0, 0.0],
        "transitions": 0, "max_stage": 0, "final_stages": [0],
        "brownout_engaged": False,
    }


def _run(tmp_path, replay_doc, live_doc, order=("replay", "live"), tol=None):
    pr = tmp_path / "replay.json"
    pl = tmp_path / "live.json"
    pr.write_text(json.dumps(replay_doc))
    pl.write_text(json.dumps(live_doc))
    paths = {"replay": str(pr), "live": str(pl)}
    argv = [paths[order[0]], paths[order[1]]]
    if tol is not None:
        argv += ["--tol", str(tol)]
    return regress.main(argv)


def test_regress_replay_within_band_passes_either_order(tmp_path, capsys):
    tiers = {"easy": {"p95_ms": 5.0}, "hard": {"p95_ms": 2100.0}}
    live = _live_artifact(tiers={"easy": {"p95_ms": 5.5},
                                 "hard": {"p95_ms": 2000.0}})
    rep = _replay_artifact(tiers)
    assert _run(tmp_path, rep, live) == 0
    assert "replay prediction within" in capsys.readouterr().out
    assert _run(tmp_path, rep, live, order=("live", "replay")) == 0


def test_regress_replay_out_of_band_is_a_misprediction(tmp_path, capsys):
    rep = _replay_artifact({"hard": {"p95_ms": 4000.0}})
    live = _live_artifact(tiers={"hard": {"p95_ms": 2000.0}})
    assert _run(tmp_path, rep, live) == 1
    assert "MISPREDICTION" in capsys.readouterr().err
    # Two-sided: a wildly optimistic prediction fails the same way.
    rep_lo = _replay_artifact({"hard": {"p95_ms": 100.0}})
    assert _run(tmp_path, rep_lo, live) == 1


def test_regress_replay_overall_fallback_for_allhard_traces(tmp_path):
    """Live artifacts without tier sections (no --mix) compare the
    replay's overall p95 against the live resident p95."""
    rep = _replay_artifact({"hard": {"p95_ms": 1900.0}})
    live = _live_artifact(resident_p95=2000.0)  # no tiers
    assert _run(tmp_path, rep, live) == 0
    rep["overall"]["p95_ms"] = 9000.0
    assert _run(tmp_path, rep, live) == 1


def test_regress_replay_workload_mismatch_exits_2(tmp_path, capsys):
    rep = _replay_artifact({"hard": {"p95_ms": 2000.0}},
                           workload_params=dict(BENCH_PARAMS, seed=8))
    live = _live_artifact(tiers={"hard": {"p95_ms": 2000.0}})
    assert _run(tmp_path, rep, live) == 2
    assert "DIFFERENT workload" in capsys.readouterr().err


def test_regress_replay_mix_normalizes_spelling(tmp_path, capsys):
    """'hard:6,easy:20' and 'easy:20,hard:6,repeat:0' are the SAME
    workload; a genuinely different mix is exit 2."""
    wl = dict(BENCH_PARAMS, mix="easy:20,hard:6,repeat:0")
    lp = dict(BENCH_PARAMS, mix="hard:6,easy:20")
    rep = _replay_artifact({"hard": {"p95_ms": 2000.0}}, workload_params=wl)
    live = _live_artifact(tiers={"hard": {"p95_ms": 2000.0}}, params=lp)
    assert _run(tmp_path, rep, live) == 0
    live2 = _live_artifact(
        tiers={"hard": {"p95_ms": 2000.0}},
        params=dict(BENCH_PARAMS, mix="easy:10,hard:6"),
    )
    assert _run(tmp_path, rep, live2) == 2
    assert "mix" in capsys.readouterr().err


def test_regress_replay_scaling_knobs_exit_2(tmp_path, capsys):
    live = _live_artifact(tiers={"hard": {"p95_ms": 2000.0}})
    assert _run(
        tmp_path,
        _replay_artifact({"hard": {"p95_ms": 2000.0}}, rate_x=10.0),
        live,
    ) == 2
    assert "rate_x" in capsys.readouterr().err
    assert _run(
        tmp_path,
        _replay_artifact({"hard": {"p95_ms": 2000.0}}, nodes=3),
        live,
    ) == 2
    assert "virtual nodes" in capsys.readouterr().err
    # A reshaped node (--slots / --queue-depth off the recorded shape) is
    # capacity exploration too (review finding): exit 2, never a
    # MISPREDICTION.
    reshaped = _replay_artifact({"hard": {"p95_ms": 2000.0}})
    reshaped["params"]["recorded"] = {"job_slots": 8, "queue_depth": 64}
    reshaped["params"]["slots"] = 2
    assert _run(tmp_path, reshaped, live) == 2
    assert "capacity exploration" in capsys.readouterr().err
    reshaped["params"]["slots"] = 8
    reshaped["params"]["queue_depth"] = 16
    assert _run(tmp_path, reshaped, live) == 2
    reshaped["params"]["queue_depth"] = 64
    assert _run(tmp_path, reshaped, live) == 0


def test_regress_zero_comparable_pairs_exits_2(tmp_path, capsys):
    """A gate that compared NOTHING must not print OK (review finding):
    a replay that shed every job (overall=None, empty tiers) against a
    live artifact with no tier sections is exit 2, not a pass."""
    rep = _replay_artifact({}, shed_total=48)
    rep["overall"] = None
    live = _live_artifact()  # no tiers section
    assert _run(tmp_path, rep, live) == 2
    assert "no comparable quantiles" in capsys.readouterr().err


def test_regress_two_replays_exit_2(tmp_path, capsys):
    rep = _replay_artifact({"hard": {"p95_ms": 2000.0}})
    assert _run(tmp_path, rep, dict(rep)) == 2
    assert "LIVE" in capsys.readouterr().err


def test_regress_bench_vs_bench_unchanged(tmp_path):
    """The pre-round-18 bench-vs-bench gate is untouched by the replay
    rules (same schema, same exit codes)."""
    a = _live_artifact()
    b = _live_artifact()
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    assert regress.main([str(pa), str(pb)]) == 0


# -- arrival-schedule determinism ----------------------------------------------


def test_arrival_offsets_match_the_live_draw_order():
    """poisson_load and the workload recorder must share ONE schedule:
    offsets are the cumulative sums of the exact gap sequence the live
    submit loop sleeps (same rng, same draw order)."""
    import random

    from benchmarks.bench_poisson import arrival_offsets, poisson_gaps

    gaps = poisson_gaps(10, 0.05, seed=7)
    rng = random.Random(7)
    want = [rng.expovariate(1.0 / 0.05) for _ in range(9)]
    assert gaps == want
    offs = arrival_offsets(10, 0.05, seed=7)
    assert offs[0] == 0.0 and len(offs) == 10
    assert offs[3] == pytest.approx(sum(want[:3]))


# -- slow lane: the recorded-trace round trip ----------------------------------


@pytest.mark.slow
def test_recorded_workload_replays_within_the_regress_band(
    tmp_path, heavy_compile_guard
):
    """The acceptance round trip (ISSUE 15): record a live mixed-corpus
    bench run as a workload trace, replay it, and the replay's per-tier
    p95 must sit inside the regress.py noise band of the live artifact
    that produced it (exit 0)."""
    from benchmarks.bench_poisson import compare_poisson, parse_mix

    out = compare_poisson(
        n_jobs=0,
        mean_gap_s=0.03,
        handicap_s=0.0,
        seed=11,
        chunk_steps=8,
        mix=parse_mix("easy:6,hard:1,repeat:3"),
        record_workload=True,
    )
    workload = out.pop("workload")
    assert workload["schema"] == WORKLOAD_SCHEMA
    assert len(workload["jobs_trace"]) == 10
    live = {
        "schema": regress.SCHEMA,
        "params": {
            "jobs": out["jobs"], "mean_gap_ms": 30.0, "handicap_ms": 0.0,
            "chunk_steps": 8, "seed": 11, "mix": "easy:6,hard:1,repeat:3",
        },
        "static": out["static"],
        "resident": out["resident"],
    }
    # Workload params carry the identical identity (mix normalized).
    assert regress._norm_mix(workload["params"]["mix"]) == regress._norm_mix(
        live["params"]["mix"]
    )
    art = replay(
        workload,
        nodes=1,
        seed=0,
        # Headroom so the replayed control loop never sheds the recorded
        # (healthy) run — any shed here would shrink the compared set.
        slo_spec="solve_p95_ms<=60000,error_rate<=0.5",
        bo_config=brownout.BrownoutConfig(quiet_s=5.0, hold_s=0.5),
    )
    assert art["completed"] == len(workload["jobs_trace"])
    assert art["shed"]["total"] == 0
    pr, pl = tmp_path / "replay.json", tmp_path / "live.json"
    pr.write_text(json.dumps(art))
    pl.write_text(json.dumps(live))
    assert regress.main([str(pr), str(pl)]) == 0
