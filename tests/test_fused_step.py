"""The fused whole-round VMEM kernel (``SolverConfig.step_impl='fused'``).

VERDICT r2 #1's contract: the fused path is a *gated strategy* — same
verdict semantics as the composite XLA step (solved / proven-unsat /
unknown-on-overflow, identical solutions on uniquely-solvable boards),
with purge/steal reacting at ``fused_steps`` granularity, so node counts
legitimately differ.  These tests pin the soundness half of that contract;
the measured 2.2x A/B rows live in BENCHMARKS.md ("The whole-round fused
kernel").  On the CPU mesh the kernel runs in Pallas interpret mode — the
same code path the TPU lane compiles natively (tests/test_tpu.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.utils.oracle import solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9


def _fused(**kw):
    kw.setdefault("min_lanes", 8)
    kw.setdefault("stack_slots", 32)
    kw.setdefault("max_steps", 4096)
    return SolverConfig(step_impl="fused", **kw)


def _unsat_board():
    g = np.asarray(HARD_9[1]).copy()
    g[1, 6] = 8  # consistent-looking wrong clue: deep exhaustion proof
    return g


def test_solves_match_oracle():
    boards = [EASY_9, *HARD_9]
    grids = jnp.asarray(np.stack(boards).astype(np.int32))
    res = solve_batch(grids, SUDOKU_9, _fused())
    assert np.asarray(res.solved).all()
    assert not np.asarray(res.unsat).any()
    for i, g in enumerate(boards):
        assert (
            np.asarray(res.solution[i]) == solve_oracle(np.asarray(g), SUDOKU_9)
        ).all(), f"board {i}"
    assert int(np.asarray(res.nodes).sum()) > 0  # hard boards needed search


def test_verdicts_agree_with_xla_step():
    """Same solved/unsat/solution verdicts as the composite step on a mixed
    corpus (node counts may differ — purge latency is fused_steps rounds)."""
    boards = np.stack([EASY_9, HARD_9[0], _unsat_board(), HARD_9[2]]).astype(
        np.int32
    )
    grids = jnp.asarray(boards)
    ref = solve_batch(grids, SUDOKU_9, SolverConfig(min_lanes=8, stack_slots=32))
    got = solve_batch(grids, SUDOKU_9, _fused())
    assert (np.asarray(got.solved) == np.asarray(ref.solved)).all()
    assert (np.asarray(got.unsat) == np.asarray(ref.unsat)).all()
    assert (np.asarray(got.solution) == np.asarray(ref.solution)).all()


def test_proven_unsat():
    res = solve_batch(jnp.asarray(_unsat_board()[None]), SUDOKU_9, _fused())
    assert not bool(res.solved[0])
    assert bool(res.unsat[0])
    assert not bool(res.overflowed[0])


def test_overflow_downgrades_to_unknown():
    """A 1-slot stack forces dropped subtrees on the unsat board: the
    verdict must be unknown (neither solved nor unsat), never a false
    proof."""
    res = solve_batch(
        jnp.asarray(_unsat_board()[None]),
        SUDOKU_9,
        _fused(stack_slots=1, min_lanes=1, lanes=1, steal=False),
    )
    assert not bool(res.solved[0])
    assert not bool(res.unsat[0]), "dropped subtrees must not prove unsat"
    assert bool(res.overflowed[0])


def test_gang_up_steals_serve_thief_lanes():
    """Extra lanes join a deep search via the XLA-side steal between
    dispatches; the job still resolves and steals actually happened."""
    res = solve_batch(
        jnp.asarray(np.asarray(HARD_9[1])[None]),
        SUDOKU_9,
        _fused(min_lanes=16, fused_steps=2),
    )
    assert bool(res.solved[0])
    assert int(np.asarray(res.steals)) > 0, "no lane ever stole work"
    assert (
        np.asarray(res.solution[0]) == solve_oracle(np.asarray(HARD_9[1]), SUDOKU_9)
    ).all()


@pytest.mark.parametrize("rules", ["basic", "extended", "subsets"])
def test_rules_tiers(rules):
    res = solve_batch(
        jnp.asarray(np.asarray(HARD_9[0])[None]), SUDOKU_9, _fused(rules=rules)
    )
    assert bool(res.solved[0])
    assert (
        np.asarray(res.solution[0]) == solve_oracle(np.asarray(HARD_9[0]), SUDOKU_9)
    ).all()


@pytest.mark.parametrize("branch", ["first", "minrem-desc", "mixed"])
def test_branch_rules(branch):
    res = solve_batch(
        jnp.asarray(np.asarray(HARD_9[0])[None]),
        SUDOKU_9,
        _fused(branch=branch),
    )
    assert bool(res.solved[0])
    assert (
        np.asarray(res.solution[0]) == solve_oracle(np.asarray(HARD_9[0]), SUDOKU_9)
    ).all()


def test_non_tile_multiple_lane_counts():
    """Lane counts that don't divide the 128-lane kernel tile are rounded
    up internally (extra lanes start idle as thieves) — the composite
    path's no-constraint contract holds for the fused path too."""
    res = solve_batch(
        jnp.asarray(np.asarray(HARD_9[0])[None]),
        SUDOKU_9,
        _fused(lanes=200, stack_slots=16),
    )
    assert bool(res.solved[0])
    assert (
        np.asarray(res.solution[0]) == solve_oracle(np.asarray(HARD_9[0]), SUDOKU_9)
    ).all()


def test_fused_rejects_branch_k3():
    with pytest.raises(ValueError, match="branch_k"):
        SolverConfig(step_impl="fused", branch_k=3)
    with pytest.raises(ValueError, match="step_impl"):
        SolverConfig(step_impl="vmem")


# --- count_all enumeration in the fused kernel (VERDICT r3 #5) -------------


def test_count_all_empty_4x4_exact_288():
    """All 288 complete 4x4 Sudoku grids, enumerated inside the kernel
    (solved lanes pop and continue instead of freezing)."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4
    from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution

    empty = np.zeros((1, 4, 4), np.int32)
    res = solve_batch(
        jnp.asarray(empty), SUDOKU_4, _fused(count_all=True, max_steps=100_000)
    )
    assert int(res.sol_count[0]) == 288
    assert bool(res.unsat[0])  # exhausted == enumeration complete
    assert not bool(res.overflowed[0])
    assert not bool(res.solved[0])  # never resolves by design
    assert is_valid_solution(np.asarray(res.solution[0]), SUDOKU_4)


def _multisolution_board(n_blank: int = 4) -> np.ndarray:
    """EASY_9 with ``n_blank`` random clues removed (62 solutions at 4 —
    verified against the native DFS; keep it modest: interpret-mode
    enumeration walks the whole tree)."""
    few = np.asarray(EASY_9).copy()
    rng = np.random.default_rng(3)
    idx = np.flatnonzero(few.ravel())
    few.ravel()[rng.choice(idx, size=n_blank, replace=False)] = 0
    return few


def test_count_all_matches_composite_on_multisolution_9x9():
    """Exact counts agree with the composite step on multi-solution boards
    (which first solution is reported may differ — counts may not)."""
    boards = np.stack([_multisolution_board(), np.asarray(EASY_9)]).astype(
        np.int32
    )
    ref = solve_batch(
        jnp.asarray(boards),
        SUDOKU_9,
        SolverConfig(
            min_lanes=8, stack_slots=32, max_steps=100_000, count_all=True
        ),
    )
    got = solve_batch(
        jnp.asarray(boards),
        SUDOKU_9,
        _fused(count_all=True, stack_slots=32, max_steps=100_000),
    )
    assert int(got.sol_count[0]) == int(ref.sol_count[0]) == 62
    assert int(got.sol_count[1]) == int(ref.sol_count[1]) == 1
    assert (np.asarray(got.unsat) == np.asarray(ref.unsat)).all()


def test_count_all_overflow_is_lower_bound_fused():
    """A 1-slot stack drops subtrees: overflow must flag the count as a
    lower bound, never a silently wrong exact claim."""
    few = _multisolution_board(8)  # 5,539 solutions: a 1-slot DFS overflows
    res = solve_batch(
        jnp.asarray(few[None].astype(np.int32)),
        SUDOKU_9,
        _fused(
            count_all=True, stack_slots=1, min_lanes=1, lanes=1, steal=False,
            max_steps=100_000,
        ),
    )
    assert bool(res.overflowed[0])


def test_count_all_fused_sharded_psum_exact():
    """Enumeration under the 8-device lane-sharded fused path: per-chip
    disjoint-subtree counts psum to the exact global model count."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_4
    from distributed_sudoku_solver_tpu.parallel import (
        make_mesh,
        solve_batch_fused_sharded,
    )

    empty = np.zeros((1, 4, 4), np.int32)
    cfg = _fused(count_all=True, min_lanes=16, max_steps=100_000)
    res = solve_batch_fused_sharded(empty, SUDOKU_4, cfg, mesh=make_mesh())
    assert int(np.asarray(res.sol_count[0])) == 288
    assert bool(np.asarray(res.unsat[0]))
    assert not bool(np.asarray(res.overflowed[0]))


def test_bulk_first_pass_fused_matches_default():
    """ops/bulk with step_impl='fused' yields the same verdicts as the
    composite first pass on a small corpus (auto mode picks fused only on
    TPU, so force it here to exercise the plumbing on the CPU mesh)."""
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk

    boards = np.stack([EASY_9, HARD_9[0], _unsat_board(), HARD_9[2]]).astype(
        np.int32
    )
    ref = solve_bulk(boards, SUDOKU_9, BulkConfig(chunk=4, stack_slots=32, step_impl="xla"))
    got = solve_bulk(boards, SUDOKU_9, BulkConfig(chunk=4, stack_slots=32, step_impl="fused"))
    assert (got.solved == ref.solved).all()
    assert (got.unsat == ref.unsat).all()
    assert (got.solution == ref.solution).all()


# --- round 6: per-surface fused_steps + in-kernel occupancy ----------------


def test_fused_steps_surface_defaults():
    """fused_steps=None resolves per SURFACE: deep on device-resident paths
    (32 — r4 re-sweep), shallow on per-chunk transfer paths (8 — e2e A/B),
    and an explicit value always wins (the portfolio pins 4, tests pin 2)."""
    from distributed_sudoku_solver_tpu.ops.frontier import (
        FUSED_STEPS_DEVICE,
        FUSED_STEPS_LINKED,
    )

    cfg = SolverConfig(step_impl="fused")
    assert cfg.fused_steps is None
    assert cfg.with_fused_steps(FUSED_STEPS_DEVICE).fused_steps == 32
    assert cfg.with_fused_steps(FUSED_STEPS_LINKED).fused_steps == 8
    pinned = SolverConfig(step_impl="fused", fused_steps=4)
    assert pinned.with_fused_steps(FUSED_STEPS_DEVICE).fused_steps == 4
    with pytest.raises(ValueError, match="fused_steps"):
        SolverConfig(fused_steps=0)


def test_bulk_first_pass_pins_linked_fused_steps():
    """The bulk first pass is a per-chunk transfer surface: its fused
    flights must run the shallow default even though solve_batch_fused's
    own (device-resident) default is deep."""
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.ops.frontier import FUSED_STEPS_LINKED

    boards = np.stack([EASY_9, HARD_9[0]]).astype(np.int32)
    trace = {}
    res = solve_bulk(
        boards,
        SUDOKU_9,
        BulkConfig(chunk=2, stack_slots=32, step_impl="fused"),
        trace=trace,
    )
    assert res.solved.all()
    assert trace["fused_steps"] == FUSED_STEPS_LINKED


def test_lane_rounds_occupancy_counter():
    """The in-kernel live-lane counter row: lane_rounds accumulates, per
    lane, the rounds it held live work — bounded by the rounds advanced,
    and nonzero exactly for lanes that ever worked."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.bitmask import encode_grid
    from distributed_sudoku_solver_tpu.ops.frontier import init_frontier
    from distributed_sudoku_solver_tpu.ops.pallas_step import (
        advance_frontier_fused,
    )

    cfg = _fused(min_lanes=8, fused_steps=2)
    grids = jnp.asarray(np.asarray(HARD_9[0])[None].astype(np.int32))
    state = init_frontier(encode_grid(grids, SUDOKU_9), cfg)
    assert int(np.asarray(state.lane_rounds).sum()) == 0
    out = advance_frontier_fused(state, jnp.int32(4096), SUDOKU_9, cfg)
    lr = np.asarray(out.lane_rounds)
    steps = int(np.asarray(out.steps))
    assert steps > 0
    assert (lr >= 0).all() and (lr <= steps).all()
    assert lr.sum() > 0, "no lane was ever recorded live"
    # The seed lane worked from round one; with steal on, thief lanes that
    # joined later show strictly smaller counts than the total rounds.
    assert lr.max() > 0


def test_sweep_unroll_prefix_is_bit_exact():
    """fused_sweep_unroll only amortizes the fixpoint loop — results
    (solutions, verdicts, node counts) are bit-identical with the prefix
    on (2, the default) and off (0, the pre-round-6 loop)."""
    boards = np.stack([EASY_9, HARD_9[0], _unsat_board()]).astype(np.int32)
    grids = jnp.asarray(boards)
    a = solve_batch(grids, SUDOKU_9, _fused(fused_sweep_unroll=0))
    b = solve_batch(grids, SUDOKU_9, _fused(fused_sweep_unroll=2))
    assert (np.asarray(a.solved) == np.asarray(b.solved)).all()
    assert (np.asarray(a.unsat) == np.asarray(b.unsat)).all()
    assert (np.asarray(a.solution) == np.asarray(b.solution)).all()
    assert (np.asarray(a.nodes) == np.asarray(b.nodes)).all()
