"""jaxck gate (analysis/jaxck.py): the compiled-layer invariants.

Lanes:
* fixture lane — synthetic jit programs (tests/data/analysis/jaxprog.py)
  driven through ``check_entry_points`` with injected registries, pinning
  that each failure mode actually FIRES: a dropped donation, an injected
  callback in a hot program, a drifted-HLO golden, an un-pinned Python
  scalar at a call site;
* golden round-trip — ``--update-golden`` writes, a re-check is clean,
  drift against the written golden is caught, re-blessing clears it;
* the gate — ``--rule jaxck --json`` over the real tree exits 0 with the
  committed goldens (covering every donate_argnums program in
  serving/ops/utils/parallel) and is byte-deterministic across runs;
* the runtime twin — a retrace guard running a representative serving
  workload twice and asserting, via jit cache sizes and jax's
  compilation event hooks, that entry points compile exactly once.
"""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import pytest

from distributed_sudoku_solver_tpu.analysis import jaxck, manifest
from distributed_sudoku_solver_tpu.analysis.common import (
    ALL_RULES,
    RULES,
    SourceModule,
)
from distributed_sudoku_solver_tpu.obs import exitcodes

REPO = Path(__file__).resolve().parent.parent
FIXTURE = Path(__file__).resolve().parent / "data" / "analysis" / "jaxprog.py"

#: Tiny canon for fixture programs: no frontier/resident specs needed.
CANON = {"geom": (2, 2), "dims": {"n": 4}, "configs": {}}


@pytest.fixture(scope="module")
def fixture_mod():
    """The fixture programs, importable as ``jaxck_fixture`` so registry
    ``fn`` strings resolve through the normal import path."""
    spec = importlib.util.spec_from_file_location("jaxck_fixture", FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["jaxck_fixture"] = mod
    spec.loader.exec_module(mod)
    yield mod
    sys.modules.pop("jaxck_fixture", None)


def entry(name, attr, *, donate=(), donation=None, hot=True, args=2, dtype="uint32"):
    return dict(
        name=name,
        fn=f"jaxck_fixture:{attr}",
        args=tuple(("array", (4, 4), dtype) for _ in range(args)),
        static={},
        donate=donate,
        donation=donation,
        hot=hot,
    )


def check(entries, tmp_path, update_golden=False, mods=(), golden="g.json"):
    findings, summary = jaxck.check_entry_points(
        entries=entries,
        canon=CANON,
        golden_path=tmp_path / golden,
        mods=mods,
        update_golden=update_golden,
    )
    return findings, summary


# -- fixture lane: each failure mode fires -------------------------------------


def test_dropped_donation_caught(fixture_mod, tmp_path):
    entries = (
        entry("fix.good", "good_thread", donate=(0,), donation="threads"),
        entry("fix.dropped", "dropped_donation", donate=(0,), donation="threads"),
    )
    findings, _ = check(entries, tmp_path, update_golden=True)
    msgs = [f.message for f in findings]
    assert len(msgs) == 1, findings
    assert "fix.dropped" in msgs[0] and "donation did not lower" in msgs[0]
    assert "0/1 donated buffers alias" in msgs[0]


def test_undeclared_donation_caught(fixture_mod, tmp_path):
    # The inverse failure: the decorator donates but the manifest entry
    # says donate=() — the registry may never under-describe the
    # donation surface (the lowering's args_info is the ground truth).
    entries = (entry("fix.good", "good_thread", donate=()),)
    findings, _ = check(entries, tmp_path, update_golden=True)
    assert len(findings) == 1, findings
    assert "manifest entry declares donate=()" in findings[0].message


def test_injected_callback_caught_in_hot_program_only(fixture_mod, tmp_path):
    hot = (entry("fix.cb", "hot_callback", args=1, dtype="float32", hot=True),)
    findings, _ = check(hot, tmp_path, update_golden=True)
    assert len(findings) == 1, findings
    assert "callback in serving-hot program" in findings[0].message
    assert "debug_callback" in findings[0].message

    cold = (entry("fix.cb", "hot_callback", args=1, dtype="float32", hot=False),)
    findings, _ = check(cold, tmp_path, update_golden=True)
    assert findings == []


def test_drift_caught_and_update_golden_round_trip(fixture_mod, tmp_path):
    v1 = (entry("fix.drift", "drifting", args=1),)
    v2 = (entry("fix.drift", "drifting_changed", args=1),)

    # No golden yet: reported, not silently clean.
    findings, _ = check(v1, tmp_path)
    assert len(findings) == 1 and "no committed golden" in findings[0].message

    # Bless v1; a re-check against the written golden is clean.
    findings, summary = check(v1, tmp_path, update_golden=True)
    assert findings == [] and summary["golden_written"]
    findings, summary = check(v1, tmp_path)
    assert findings == [] and summary["drifted"] == []

    # The injected HLO change is caught, attributed, priced.
    findings, summary = check(v2, tmp_path)
    assert len(findings) == 1, findings
    assert "HLO drift" in findings[0].message
    assert "invalidates the XLA cache" in findings[0].message
    assert summary["drifted"] == ["fix.drift"]

    # Re-bless: drift recorded in the summary, absent from findings.
    findings, summary = check(v2, tmp_path, update_golden=True)
    assert findings == [] and summary["drifted"] == ["fix.drift"]
    findings, _ = check(v2, tmp_path)
    assert findings == []


def test_unpinned_scalar_call_site_caught(fixture_mod, tmp_path):
    mods = [SourceModule(FIXTURE, "jaxprog.py", "jaxck_fixture")]
    entries = (entry("fix.good", "good_thread", donate=(0,), donation="threads"),)
    findings, _ = check(entries, tmp_path, update_golden=True, mods=mods)
    live = [f for f in findings if not f.waived]
    assert len(live) == 1, findings
    assert "un-pinned Python scalar" in live[0].message
    assert "'y' of good_thread()" in live[0].message


def test_stale_golden_entry_reported(fixture_mod, tmp_path):
    v1 = (entry("fix.drift", "drifting", args=1),)
    check(v1, tmp_path, update_golden=True)
    findings, _ = check((), tmp_path)  # program removed from the registry
    assert len(findings) == 1
    assert "golden entry has no ENTRY_POINTS program" in findings[0].message


# -- the registry covers the donation surface ----------------------------------


def test_registry_covers_every_donate_argnums_program():
    """Completeness pin: every function carrying a ``donate_argnums``
    decorator in serving/ops/utils/parallel has an ENTRY_POINTS record —
    so nobody can add a donated program the compiled gate never sees.
    AST-based: decorator keyword order and line wrapping don't matter."""
    import ast

    registered = {e["fn"].split(":")[1] for e in manifest.ENTRY_POINTS}
    pkg = REPO / "distributed_sudoku_solver_tpu"
    missing = []
    for sub in ("serving", "ops", "utils", "parallel"):
        for path in sorted((pkg / sub).glob("*.py")):
            tree = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                donated = any(
                    isinstance(dec, ast.Call)
                    and any(kw.arg == "donate_argnums" for kw in dec.keywords)
                    for dec in node.decorator_list
                )
                if donated and node.name not in registered:
                    missing.append(f"{path.name}:{node.name}")
    assert not missing, f"donated programs with no ENTRY_POINTS record: {missing}"


def test_default_lane_excludes_jaxck():
    assert "jaxck" not in RULES
    assert "jaxck" in ALL_RULES


# -- the gate over the real tree -----------------------------------------------


def test_jaxck_clean_on_head_and_json_deterministic():
    """The acceptance pin: ``--rule jaxck`` exits 0 on HEAD against the
    committed goldens, and two ``--json`` runs are byte-identical (the
    fingerprints are canonicalized: nothing address- or run-varying
    survives into the report)."""
    cmd = [
        sys.executable, "-m", "distributed_sudoku_solver_tpu.analysis",
        "--rule", "jaxck", "--json",
    ]
    runs = [
        subprocess.run(cmd, capture_output=True, text=True, cwd=REPO, timeout=300)
        for _ in range(2)
    ]
    for proc in runs:
        assert proc.returncode == exitcodes.EXIT_CLEAN, (
            proc.stdout[-4000:], proc.stderr[-4000:],
        )
    assert runs[0].stdout == runs[1].stdout
    report = json.loads(runs[0].stdout)
    assert report["rules"]["jaxck"]["violations"] == []
    assert report["jaxck"]["programs"] == len(manifest.ENTRY_POINTS)
    assert report["jaxck"]["drifted"] == []


def test_goldens_committed_for_every_entry_point():
    golden = json.loads((REPO / "distributed_sudoku_solver_tpu" / "analysis"
                         / "goldens" / "jaxck.json").read_text())
    names = {e["name"] for e in manifest.ENTRY_POINTS}
    assert set(golden["programs"]) == names
    for name, rec in golden["programs"].items():
        assert rec["fingerprint"] and rec["eqns"] > 0, name


# -- the runtime twin: retrace guard -------------------------------------------


def _entry_fns():
    out = {}
    for e in manifest.ENTRY_POINTS:
        try:
            out[e["name"]] = jaxck._load_entry(e["fn"])
        except Exception:  # pragma: no cover - import failure is jaxck's beat
            pass
    return out


def test_retrace_guard_one_compile_per_entry_point():
    """Run a representative serving workload twice (same shapes, fresh
    values) and prove, per entry point, exactly one compilation: the
    second wave adds ZERO cache entries and fires ZERO backend-compile
    events.  Sequential single-job submits keep the admission batch
    width — a static arg — deterministic.

    Round 15: the jax monitoring listener this guard used to register
    inline lives on the production seam now (``obs/compilewatch.py``) —
    test and production share ONE listener, and the guard additionally
    pins that the watcher's per-program counts equal its own cache-size
    deltas (the same attribution ground truth, derived independently).
    """
    from distributed_sudoku_solver_tpu.obs import compilewatch
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    fns = _entry_fns()
    boards = [HARD_9[0], HARD_9[1 % len(HARD_9)]]
    displays = {
        n: e.get("display") or n.rsplit(".", 1)[-1]
        for n, e in ((e["name"], e) for e in manifest.ENTRY_POINTS)
    }

    # stack_slots=18 is this guard's private static config: no other test
    # uses it, so module-level jit caches shared across the pytest
    # process cannot pre-warm wave 1 — the first wave provably compiles
    # (delta 1) and the second provably does not (delta 0).
    watch = compilewatch.CompileWatch(warmup_s=3600.0)
    with compilewatch.installed(watch):
        eng = SolverEngine(
            config=SolverConfig(min_lanes=8, stack_slots=18), max_batch=8
        ).start()
        try:
            def wave():
                for board in boards:
                    job = eng.submit(board)
                    assert job.wait(120) and job.solved

            before = {n: f._cache_size() for n, f in fns.items()}
            wave()
            after1 = {n: f._cache_size() for n, f in fns.items()}
            deltas1 = {n: after1[n] - before[n] for n in fns}
            # One compilation per entry point the workload exercises — a
            # retrace fork (weak-type churn, unstable statics) shows as 2+.
            assert all(d in (0, 1) for d in deltas1.values()), deltas1
            exercised = {n for n, d in deltas1.items() if d == 1}
            assert "utils.checkpoint.advance_frontier_status" in exercised, (
                deltas1
            )
            assert "serving.engine._finalize_jit" in exercised, deltas1

            # The watcher's attribution agrees with the guard's own
            # cache-size deltas, program by program (satellite: one
            # listener, two consumers, same truth).  Two polls: a
            # trailing unregistered compile must survive one pass
            # (insertion-race tolerance) before it lands.
            watch.program_counts()
            counts1 = watch.program_counts()
            for n, d in deltas1.items():
                assert counts1.get(displays[n], 0) == d, (n, d, counts1)

            total1 = watch.metrics()["compiles_total"]
            wave()
            after2 = {n: f._cache_size() for n, f in fns.items()}
            assert after2 == after1, {
                n: (after1[n], after2[n]) for n in fns if after1[n] != after2[n]
            }
            # Zero compile events in wave 2 — the watch saw nothing new.
            assert watch.metrics()["compiles_total"] == total1
            assert watch.program_counts() == counts1
        finally:
            eng.stop(timeout=5)


def test_entry_point_displays_unique_and_shared_with_compilewatch():
    """The manifest's display names are the compiled layer's shared
    vocabulary: unique (jaxck enforces it as a finding too), and exactly
    what the production compile watch keys its /metrics series on."""
    from distributed_sudoku_solver_tpu.obs import compilewatch

    displays = [
        e.get("display") or e["name"].rsplit(".", 1)[-1]
        for e in manifest.ENTRY_POINTS
    ]
    assert all(e.get("display") for e in manifest.ENTRY_POINTS), (
        "every ENTRY_POINTS record carries an explicit display name"
    )
    assert len(set(displays)) == len(displays), displays
    for e in manifest.ENTRY_POINTS:
        assert compilewatch.display_name(e["name"]) == e["display"]


def test_duplicate_display_is_a_jaxck_finding(fixture_mod, tmp_path):
    entries = (
        dict(entry("fix.a", "good_thread", donate=(0,), donation="threads"),
             display="dup"),
        dict(entry("fix.b", "drifting", args=1), display="dup"),
    )
    findings, _ = check(entries, tmp_path, update_golden=True)
    dups = [f for f in findings if "duplicate display" in f.message]
    assert len(dups) == 1, findings
