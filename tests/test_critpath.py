"""Round-15 per-job critical-path attribution (obs/critpath.py).

* **Decomposition unit lane** — hand-built spans on a synthetic clock:
  the phase partition is exact (phases sum to the end-to-end wall),
  overlaps resolve by priority (sync beats dispatch — the always-ahead
  loop's chunk k+1 dispatch span overlapping chunk k's sync), and gaps
  land in ``other``.
* **Monitor lane** — aggregation into mergeable per-phase histograms +
  attribution shares; the slow-job watchdog (explicit and SLO-derived
  thresholds) dumps the critical path with a cooldown.
* **Acceptance** — the phases-sum-to-wall contract holds on BOTH clock
  domains the ISSUE names: a live engine on the real clock (via the
  HTTP ``?analyze=1`` surface, tests/test_api.py) and a 2-node simnet
  ring on the virtual clock (here), where the stitched trace's wire
  spans attribute cross-node time.
"""

import json
import logging
import os

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.obs import critpath, slo, trace
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)


@pytest.fixture(autouse=True)
def _clean_seams():
    yield
    critpath.install(None)
    slo.install(None)
    trace.install(None)


def _span(name, site, t0, t1, trace_id="u", node="n0"):
    return {
        "id": f"{node}/{t0}", "trace": trace_id, "name": name, "site": site,
        "t0": float(t0), "t1": float(t1), "node": node, "uuids": [],
        "attrs": {},
    }


def _assert_partition(d):
    s = sum(d["phases_ms"].values())
    assert s == pytest.approx(
        d["end_to_end_ms"], rel=critpath.SUM_TOLERANCE
    ), (s, d["end_to_end_ms"])


# -- decomposition unit lane ---------------------------------------------------


def test_decompose_partitions_the_job_window_exactly():
    spans = [
        _span("admission", "engine.launch", 0.0, 1.0),
        _span("chunk.dispatch", "engine.advance", 1.0, 1.2),
        _span("chunk.sync", "fetch.status", 1.2, 2.0),
        _span("verdict.sync", "fetch.event", 2.0, 2.2),
        _span("send.TASK", "cluster.send", 2.2, 2.3),
        _span("recovery.requeue", "engine.recovery", 2.3, 2.4),
        _span("resolve", "engine.resolve", 2.5, 2.5),
    ]
    d = critpath.decompose(spans)
    assert d["end_to_end_ms"] == pytest.approx(2500.0)
    p = d["phases_ms"]
    assert p["queue"] == pytest.approx(1000.0)
    assert p["dispatch"] == pytest.approx(200.0)
    assert p["sync"] == pytest.approx(800.0)
    assert p["event"] == pytest.approx(200.0)
    assert p["wire"] == pytest.approx(100.0)
    assert p["recovery"] == pytest.approx(100.0)
    assert p["other"] == pytest.approx(100.0)  # the 2.4 -> 2.5 gap
    _assert_partition(d)
    assert d["shares"]["queue"] == pytest.approx(0.4)


def test_decompose_overlaps_resolve_by_priority():
    """The always-ahead loop's shape: chunk k+1's dispatch span overlaps
    chunk k's sync — the overlapped time counts once, as sync (higher
    priority), never double."""
    spans = [
        _span("chunk.dispatch", "engine.advance", 0.0, 1.0),
        _span("chunk.sync", "fetch.status", 0.5, 1.5),
        _span("resolve", "engine.resolve", 1.5, 1.5),
    ]
    d = critpath.decompose(spans)
    assert d["phases_ms"]["sync"] == pytest.approx(1000.0)
    assert d["phases_ms"]["dispatch"] == pytest.approx(500.0)
    _assert_partition(d)


def test_decompose_edge_cases():
    assert critpath.decompose([]) is None
    # Zero-width window: nothing to attribute.
    assert critpath.decompose(
        [_span("resolve", "engine.resolve", 1.0, 1.0)]
    ) is None
    # Markers (http.solve/resolve) bound the window but claim no time;
    # the http wall is echoed separately.
    spans = [
        _span("http.solve", "http", 0.0, 3.0),
        _span("admission", "engine.launch", 0.5, 1.0),
        _span("resolve", "engine.resolve", 2.0, 2.0),
    ]
    d = critpath.decompose(spans)
    assert d["end_to_end_ms"] == pytest.approx(2000.0)
    assert d["http_ms"] == pytest.approx(3000.0)
    assert d["phases_ms"]["queue"] == pytest.approx(500.0)
    _assert_partition(d)


def test_decompose_megastep_flight_is_dispatch_not_sync():
    """The round-16 decompose pin (ISSUE 16 accounting contract): a
    megastep flight's in-graph loop time classifies as DISPATCH-
    overlapped device work, never host sync.  The flight blocks the host
    in ``host_fetch``, but that wall IS the device loop plus exactly one
    floor — calling it sync would tell the operator to attack a floor
    the megastep already pays once.  The flight-wide span carries the
    dispatch site ``megastep.advance``; the fetch span's site
    ``megastep.fetch.status`` is a MARKER (claims no time), deliberately
    NOT in ``_SYNC_SITES``."""
    assert critpath.classify(
        _span("megastep.sync", "megastep.fetch.status", 0, 1)
    ) is None
    assert critpath.classify(
        _span("megastep.chunk.dispatch", "megastep.advance", 0, 1)
    ) == "dispatch"
    spans = [
        _span("admission", "megastep.attach", 0.0, 1.0),
        # The whole flight as one dispatch span; the fetch marker sits
        # inside it (the sync blocked 2.3->2.5 of device-loop wall).
        _span("megastep.chunk.dispatch", "megastep.advance", 1.0, 2.5),
        _span("megastep.sync", "megastep.fetch.status", 2.3, 2.5),
        _span("resolve", "engine.resolve", 2.5, 2.5),
    ]
    d = critpath.decompose(spans)
    assert d["end_to_end_ms"] == pytest.approx(2500.0)
    p = d["phases_ms"]
    assert p["queue"] == pytest.approx(1000.0)
    assert p["dispatch"] == pytest.approx(1500.0)  # the whole flight
    assert p.get("sync", 0.0) == pytest.approx(0.0)  # NOT host sync
    _assert_partition(d)


def test_live_megastep_trace_decomposes_as_dispatch():
    """The same pin on a real flight: trace a latency-mode solve and
    decompose its spans — the flight wall lands in dispatch, sync stays
    zero, and the partition still sums to the end-to-end wall."""
    from distributed_sudoku_solver_tpu.serving.megastep import MegastepConfig

    rec = trace.TraceRecorder(ring=4096)
    trace.install(rec)
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        latency_mode=True,
        megastep=MegastepConfig(gang_lanes=8, chunk_steps=2, max_chunks=64),
    ).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
    finally:
        eng.stop(timeout=2)
        trace.install(None)
    d = critpath.decompose(rec.spans(j.uuid))
    assert d is not None
    assert d["phases_ms"]["dispatch"] > 0.0
    assert d["phases_ms"].get("sync", 0.0) == 0.0
    _assert_partition(d)


# -- monitor lane --------------------------------------------------------------


def _feed(rec, uuid, t0=0.0):
    rec.record(uuid, "admission", "engine.launch", t0 + 0.0, t1=t0 + 0.1)
    rec.record(None, "chunk.sync", "fetch.status", t0 + 0.1, t1=t0 + 0.4,
               uuids=[uuid])
    rec.event(uuid, "resolve", "engine.resolve")


def test_monitor_aggregates_hists_and_shares(tmp_path):
    t = [0.5]
    rec = trace.TraceRecorder(clock=lambda: t[0])
    mon = critpath.CritPathMonitor(clock=lambda: t[0])
    with trace.installed(rec), critpath.installed(mon):
        for i in range(3):
            _feed(rec, f"u{i}")
            mon.observe_job(f"u{i}", 0.5)
    m = mon.metrics()
    assert m["jobs"] == 3
    assert m["attribution_ms"]["sync"] == pytest.approx(900.0)
    assert m["attribution_ms"]["queue"] == pytest.approx(300.0)
    assert m["shares_pct"]["sync"] == pytest.approx(60.0)
    assert m["slow_jobs"] == 0 and "threshold_ms" not in m
    hd = mon.hist_dicts()
    assert sum(hd["critpath_sync_ms"]["counts"]) == 3
    # A flight-level span attributes through its uuids list, so the
    # multi-job chunk span landed in every job's decomposition.
    assert sum(hd["critpath_queue_ms"]["counts"]) == 3


def test_watchdog_dumps_with_cooldown_and_slo_derived_threshold(
    tmp_path, caplog,
):
    t = [1.0]
    rec = trace.TraceRecorder(clock=lambda: t[0], dump_dir=str(tmp_path))
    mon = critpath.CritPathMonitor(dump_cooldown_s=30.0, clock=lambda: t[0])
    # No threshold anywhere: the watchdog is off.
    with trace.installed(rec), critpath.installed(mon):
        _feed(rec, "ua")
        mon.observe_job("ua", 9.9)
        assert mon.slow_jobs == 0

        # SLO-derived: the smallest latency objective's threshold.
        slo.install(
            slo.SloMonitor(
                slo.parse_slo("solve_p95_ms<=250,job_p99_ms<=400"),
                clock=lambda: t[0],
            )
        )
        assert mon.threshold_ms() == 250.0
        with caplog.at_level(logging.WARNING):
            _feed(rec, "ub")
            mon.observe_job("ub", 0.5)  # 500 ms > 250 ms
        assert mon.slow_jobs == 1 and mon.slow_dumps == 1
        dumps = [f for f in os.listdir(tmp_path) if "slow_job" in f]
        assert len(dumps) == 1
        doc = json.loads((tmp_path / dumps[0]).read_text())
        assert doc["metrics"]["uuid"] == "ub"
        _assert_partition(doc["metrics"]["analysis"])
        assert any("[critpath] slow job" in r.getMessage()
                   for r in caplog.records)

        # Cooldown: a storm costs one dump per window...
        _feed(rec, "uc")
        mon.observe_job("uc", 0.5)
        assert mon.slow_jobs == 2 and mon.slow_dumps == 1
        # ...and the window expiring re-allows.
        t[0] += 31.0
        _feed(rec, "ud")
        mon.observe_job("ud", 0.5)
        assert mon.slow_dumps == 2
    # An explicit slow_ms overrides the SLO derivation.
    assert critpath.CritPathMonitor(slow_ms=7.0).threshold_ms() == 7.0


def test_live_engine_critpath_metrics(heavy_compile_guard):
    """A traced solve on the real clock: the engine exports the critpath
    section, the per-phase hists join the mergeable `hist` keyspace, and
    the decomposition of the real trace partitions the job's wall."""
    rec = trace.TraceRecorder(ring=8192)
    mon = critpath.CritPathMonitor()
    with trace.installed(rec), critpath.installed(mon):
        eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
        try:
            j = eng.submit(HARD_9[1])
            assert j.wait(180) and j.solved, j.error
            m = eng.metrics()
        finally:
            eng.stop(timeout=2)
    assert m["critpath"]["jobs"] >= 1
    assert any(k.startswith("critpath_") for k in m["hist"])
    d = critpath.decompose(rec.spans(j.uuid))
    _assert_partition(d)
    assert d["phases_ms"]["sync"] > 0  # the per-chunk status fetches


# -- simnet acceptance: the virtual-clock half of the sum contract -------------


@pytest.mark.simnet
def test_stitched_two_node_trace_partitions_on_the_virtual_clock(tmp_path):
    """A remote job on a 2-node simnet ring: the stitched trace (wire
    spans from both nodes, admission/resolve from the worker) decomposes
    into phases that sum to the end-to-end wall within the documented
    tolerance — entirely on the virtual clock, no sleeps (the simnet
    purity guard enforces it)."""
    from distributed_sudoku_solver_tpu.cluster.node import (
        ClusterConfig,
        ClusterNode,
    )
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until

    from tests.test_cluster import oracle_solve_fn

    cfg = ClusterConfig(
        heartbeat_s=0.25, fail_factor=8.0, io_timeout_s=2.0, needwork=False,
        progress_interval_s=0.0, retry_delay_s=0.1, tombstone_probe_s=600.0,
    )
    net = SimNet()
    rec = trace.TraceRecorder(ring=8192, clock=net.clock.now, node="driver")
    mon = critpath.CritPathMonitor()
    ea = eb = a = b = None
    try:
        with trace.installed(rec), critpath.installed(mon):
            ea = SolverEngine(
                solve_fn=oracle_solve_fn(), batch_window_s=0.001
            ).start()
            eb = SolverEngine(
                solve_fn=oracle_solve_fn(), batch_window_s=0.001
            ).start()
            a = ClusterNode(ea, config=cfg, transport=net.transport(),
                            clock=net.clock).start()
            b = ClusterNode(eb, anchor=a.addr, config=cfg,
                            transport=net.transport(), clock=net.clock).start()
            assert wait_until(
                net, lambda: len(a.network) == 2 and len(b.network) == 2,
                timeout=60,
            ), "ring never formed"
            job = a._submit_remote(np.asarray(EASY_9, np.int32), b.addr_s)
            assert wait_until(net, lambda: job.done.is_set(), timeout=240), (
                "remote job never resolved"
            )
            assert job.solved

            spans = rec.spans(job.uuid)
            nodes = {s["node"] for s in spans}
            assert {a.addr_s, b.addr_s} <= nodes, nodes
            d = critpath.decompose(spans)
            _assert_partition(d)
            # Cross-node frames are present and classified as wire (the
            # virtual clock stands still inside a simnet send, so their
            # WALLS are legitimately zero — the real-clock twin in
            # tests/test_api.py measures nonzero phases); every
            # timestamp rode the virtual clock.
            names = {s["name"] for s in spans}
            assert {"send.TASK", "recv.TASK"} <= names, names
            assert all(
                critpath.classify(s) == "wire"
                for s in spans
                if s["name"].startswith(("send.", "recv."))
            )
            assert all(0.0 <= s["t0"] <= s["t1"] for s in spans)
            assert set(d["nodes"]) >= {a.addr_s, b.addr_s}
            # The monitor aggregated the worker-side resolution too.
            assert mon.metrics()["jobs"] >= 1
    finally:
        for n in (a, b):
            if n is not None:
                n.kill()
        for e in (ea, eb):
            if e is not None:
                e.stop(timeout=1)
        net.close()
