"""Scored branch-ordering heads (ISSUE 19, ROADMAP #4).

Contract under test, per head:

* ``head:minrem`` is **bit-exact** to the legacy ``minrem`` rule on BOTH
  step implementations — same node counts, same solutions, same verdicts
  (the head re-derives the historical packed key integer-for-integer).
* ``head:cw-slack`` / ``head:mlp`` relax to **verdict-equality**: the
  solved/unsat masks must match minrem's, solutions must be valid (clue
  -preserving, unit-complete), and unsat verdicts are cross-checked by an
  exhaustive ``count_all`` enumeration finding zero models.
* the numpy feature maps the trainer reads (``features_np``) must rank
  identically to the in-graph maps the mlp head serves — train/serve skew
  here silently mis-ranks every branch.

Plus the satellite seams: config-time branch validation (SolverConfig and
the board-sharded reject path), the opt-in ordering trace recorder, and
the learned easy-score threshold fit.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.obs import ordertrace
from distributed_sudoku_solver_tpu.ops import ordering
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.ops.solve import solve_batch
from distributed_sudoku_solver_tpu.parallel import validate_banded_config
from distributed_sudoku_solver_tpu.serving.frontdoor.learn import fit_easy_score
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9


def _cfg(branch: str, **kw) -> SolverConfig:
    kw.setdefault("min_lanes", 8)
    kw.setdefault("stack_slots", 32)
    kw.setdefault("max_steps", 4096)
    return SolverConfig(branch=branch, **kw)


def _unsat_board():
    g = np.asarray(HARD_9[1]).copy()
    g[1, 6] = 8  # consistent-looking wrong clue: needs deep exhaustion
    return g


def _mixed_grids():
    return jnp.asarray(
        np.stack([EASY_9, HARD_9[0], _unsat_board(), HARD_9[2]]).astype(np.int32)
    )


# -- rule validation -----------------------------------------------------------


def test_validate_branch_accepts_all_shipped_rules():
    for rule in (*ordering.LEGACY_RULES, *(f"head:{h}" for h in ordering.HEAD_NAMES)):
        ordering.validate_branch(rule)  # must not raise


@pytest.mark.parametrize("rule", ["head:nope", "bogus", "head:", "minrem "])
def test_validate_branch_rejects_unknown(rule):
    with pytest.raises(ValueError):
        ordering.validate_branch(rule)


def test_solver_config_validates_branch_at_construction():
    # The satellite's point: a typo'd rule fails where the CLI/engine/HTTP
    # boundary can still answer 4xx, not mid-trace inside a jit.
    with pytest.raises(ValueError):
        SolverConfig(branch="head:typo")


def test_banded_config_rejects_batch_only_rules_loudly():
    for rule in ("mixed", "minrem-desc", "head:cw-slack"):
        with pytest.raises(ValueError, match="board-sharded"):
            validate_banded_config(SolverConfig(branch=rule))
    validate_banded_config(SolverConfig(branch="minrem"))
    validate_banded_config(SolverConfig(branch="first"))


# -- pack_key ------------------------------------------------------------------


def test_pack_key_unique_per_cell_and_masks_decided():
    n = 9
    cells = np.arange(n * n, dtype=np.int32)
    score = np.full(n * n, 3.0, dtype=np.float32)  # ties everywhere
    und = np.ones(n * n, bool)
    und[5] = False
    key = np.asarray(
        ordering.pack_key(jnp.asarray(score), jnp.asarray(und), jnp.asarray(cells), n, 1)
    )
    assert key[5] == ordering.BIG
    live = np.delete(key, 5)
    assert len(set(live.tolist())) == len(live)  # cell index breaks every tie
    # argmin == lowest cell among the tied minimum scores
    assert int(key.argmin()) == 0


def test_pack_key_clips_runaway_scores_under_big():
    n = 9
    key = np.asarray(
        ordering.pack_key(
            jnp.asarray(np.float32(1e9)), jnp.asarray(True), jnp.asarray(7), n, 4096
        )
    )
    assert 0 < int(key) < ordering.BIG


# -- head:minrem bit-exactness -------------------------------------------------


@pytest.mark.parametrize("step_impl", ["xla", "fused"])
def test_head_minrem_bit_exact(step_impl):
    grids = _mixed_grids()
    ref = solve_batch(grids, SUDOKU_9, _cfg("minrem", step_impl=step_impl))
    got = solve_batch(grids, SUDOKU_9, _cfg("head:minrem", step_impl=step_impl))
    np.testing.assert_array_equal(np.asarray(got.solved), np.asarray(ref.solved))
    np.testing.assert_array_equal(np.asarray(got.unsat), np.asarray(ref.unsat))
    np.testing.assert_array_equal(np.asarray(got.nodes), np.asarray(ref.nodes))
    np.testing.assert_array_equal(np.asarray(got.steps), np.asarray(ref.steps))
    np.testing.assert_array_equal(np.asarray(got.solution), np.asarray(ref.solution))


# -- scored heads: verdict equality --------------------------------------------


@pytest.mark.parametrize("branch", ["head:cw-slack", "head:mlp"])
@pytest.mark.parametrize("step_impl", ["xla", "fused"])
def test_scored_heads_verdict_equal(branch, step_impl):
    boards = np.stack([EASY_9, HARD_9[0], _unsat_board(), HARD_9[2]]).astype(np.int32)
    grids = jnp.asarray(boards)
    ref = solve_batch(grids, SUDOKU_9, _cfg("minrem", step_impl=step_impl))
    got = solve_batch(grids, SUDOKU_9, _cfg(branch, step_impl=step_impl))
    np.testing.assert_array_equal(np.asarray(got.solved), np.asarray(ref.solved))
    np.testing.assert_array_equal(np.asarray(got.unsat), np.asarray(ref.unsat))
    for i in range(len(boards)):
        if not bool(np.asarray(got.solved)[i]):
            continue
        sol = np.asarray(got.solution[i])
        assert is_valid_solution(sol, SUDOKU_9)
        clue = boards[i] > 0
        assert (sol[clue] == boards[i][clue]).all(), f"board {i} dropped a clue"


def test_scored_head_unsat_cross_checked_by_count_all():
    # The verdict-equality contract's teeth: a head claiming unsat must
    # agree with an exhaustive enumeration finding zero models.
    grids = jnp.asarray(_unsat_board()[None].astype(np.int32))
    cfg = _cfg("head:cw-slack")
    res = solve_batch(grids, SUDOKU_9, cfg)
    assert bool(np.asarray(res.unsat)[0])
    cnt = solve_batch(grids, SUDOKU_9, dataclasses.replace(cfg, count_all=True))
    assert int(np.asarray(cnt.sol_count)[0]) == 0
    assert not bool(np.asarray(cnt.overflowed)[0])  # the count is complete


# -- train/serve feature parity ------------------------------------------------


def test_features_np_matches_in_graph_maps():
    g = np.asarray(HARD_9[0], dtype=np.int64)
    n = 9
    full = (1 << n) - 1
    m = np.full((n, n), full, dtype=np.int64)
    nz = g > 0
    m[nz] = np.int64(1) << (g[nz] - 1)
    m, status = ordering._np_propagate(m, SUDOKU_9)
    assert status == "open"  # a hard board: propagation alone cannot close it

    host = ordering.features_np(m, SUDOKU_9)  # [n, n, 7]

    head = ordering.get_head("head:mlp")
    cand = jnp.asarray(m[None].astype(np.uint32))  # [1, n, n] lanes layout
    feats = head._features(
        cand, SUDOKU_9, unit_sum=lambda x: ordering._unit_sums_lanes(x, SUDOKU_9)
    )
    graph = np.stack([np.asarray(f)[0] for f in feats], axis=-1)
    np.testing.assert_allclose(graph, host, rtol=0, atol=1e-6)


def test_mlp_weights_committed_and_hashable():
    head = ordering.get_head("head:mlp")
    assert ordering.get_head("head:mlp") is head  # lru: one instance, one jit key
    hash(head)  # jit-static requirement
    f = len(head.w1)
    assert f == 7  # the feature contract _cell_features pins
    assert all(len(row) == len(head.b1) for row in head.w1)
    assert len(head.w2) == len(head.b1)


def test_load_mlp_weights_rejects_unknown_schema(tmp_path):
    p = tmp_path / "w.json"
    p.write_text(json.dumps({"schema": "nope/9"}))
    with pytest.raises(ValueError, match="schema"):
        ordering.load_mlp_weights(str(p))


# -- the host-side branch-example recorder -------------------------------------


def test_record_branch_examples_covers_hard_board():
    examples, nodes = ordering.record_branch_examples(HARD_9[0], SUDOKU_9)
    assert nodes > 0 and examples
    for ex in examples:
        assert len(ex["features"]) == 7
        assert ex["pc"] >= 2  # only undecided cells branch
        assert ex["nodes"] >= 1  # every journaled branch opened a subtree


# -- the opt-in ordering trace -------------------------------------------------


def test_ordertrace_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "ot.jsonl")
    with ordertrace.installed(ordertrace.OrderTraceRecorder(path, sample_grids=2)):
        rec = ordertrace.active()
        assert rec is not None
        rec.route("u1", 40, 50, "native", 1.5, True, False)
        rec.route("u2", 80, 55, "device", 9.0, True, False, nodes=12)
        for _ in range(4):  # sample_grids=2 -> records grids 1 and 3
            rec.grid(np.asarray(EASY_9), 9)
    assert ordertrace.active() is None  # scope always uninstalls
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"kind": "route", "tr')  # torn tail from a crash
    events = ordertrace.read_events(path)
    kinds = [e["kind"] for e in events]
    assert kinds == ["route", "route", "grid", "grid"]
    assert events[1]["nodes"] == 12 and events[1]["route"] == "device"
    assert len(events[2]["grid"]) == 81


# -- the learned easy-score threshold ------------------------------------------


def _route_events(rows):
    return [
        {"kind": "route", "score": s, "route": r, "wall_ms": w, "solved": True,
         "unsat": False}
        for s, r, w in rows
    ]


def test_fit_easy_score_moves_threshold_to_the_crossover():
    # Native is cheap up to score 100 and catastrophic beyond; device is a
    # flat 5 ms.  The optimal cut is therefore AT 100, not the default 64.
    rows = []
    for s in (20, 40, 60, 80, 100):
        rows += [(s, "native", 1.0)] * 4 + [(s, "device", 5.0)] * 4
    for s in (120, 140):
        rows += [(s, "native", 50.0)] * 4 + [(s, "device", 5.0)] * 4
    t, report = fit_easy_score(_route_events(rows), default=64, min_samples=8)
    assert report["fitted"]
    assert t == 100
    assert report["cost_best"] < report["cost_default"]


def test_fit_easy_score_keeps_default_on_thin_journal():
    rows = [(40, "native", 1.0)] * 3 + [(90, "device", 5.0)] * 20
    t, report = fit_easy_score(_route_events(rows), default=64, min_samples=8)
    assert t == 64
    assert not report["fitted"]
    assert "needs >=" in report["reason"]
