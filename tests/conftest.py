"""Test harness config: run everything on a virtual 8-device CPU mesh.

The TPU-world replacement for the reference's loopback-multiprocess testing
methodology (SURVEY.md §4): real shard_map collectives on fake devices.
Must run before jax initializes any backend; the heavy lifting (including
evicting an already-registered TPU-tunnel plugin) lives in
``utils/cpu_backend.py``.
"""

import os

# TPU lane (`TPU_TESTS=1 pytest -m tpu`): keep the real backend so the
# Pallas/Mosaic kernels compile on hardware instead of interpret mode —
# the regression net for lowering breakage (ROADMAP r1 #9).  Everything
# else runs on the virtual 8-device CPU mesh.
TPU_LANE = os.environ.get("TPU_TESTS") == "1"

if not TPU_LANE:
    # Env first, in case importing the package (below) is what first imports jax.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )

    from distributed_sudoku_solver_tpu.utils.cpu_backend import force_cpu_backend

    force_cpu_backend(n_devices=8)

    # Persistent XLA compilation cache, exactly as the CLI enables for every
    # command (bench.py / cli.py): the suite's wall clock is dominated by
    # XLA:CPU compiles of large programs (fused-kernel interpreter graphs,
    # subsets sweeps, shard_map bodies), and on this single-core container
    # a warm cache cuts the full tier-1 run by minutes.  The cache rides
    # the gitignored .cache/ dir and is keyed by computation hash, so
    # staleness is not a correctness concern.
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), ".cache", "xla"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# --------------------------------------------------------------------------
# XLA:CPU segfault hazard — the structural guard (VERDICT r5 weak #4).
#
# Very large late-suite compiles can segfault the native XLA:CPU compiler
# when hundreds of earlier compiled executables are still resident in the
# process: observed twice on 2026-07-31 at the giant-geometry subsets-sweep
# compile (the suite's largest), reproducibly passing in isolation and in
# fresh processes — the correlate is allocator pressure from the
# accumulated executables, not the compile itself.  The round-5 band-aid
# was a test-local ``jax.clear_caches()`` in test_subsets.py, which only
# protected the one compile that had already crashed; any future test
# adding a bigger late-suite compile re-rolled the dice.  The fixture below
# makes the mitigation structural: any test about to run an outsized
# compile requests ``heavy_compile_guard``, and the caches are dropped ONLY
# when the live-executable census says the process is actually crowded —
# early-suite callers keep their warm caches.
# --------------------------------------------------------------------------

import pytest

# Drop compiled-executable caches above this many resident executables.
# The 2026-07-31 crashes happened with "a few hundred" resident; 100 clears
# well below the observed danger zone while never firing for a test run in
# isolation (repro runs keep their caches and their speed).
HEAVY_COMPILE_EXEC_THRESHOLD = 100


def _resident_executable_count() -> int:
    """Best-effort census of live compiled executables in this process.

    Uses the PjRt client's live-executable list where the backend exposes
    it; an un-countable backend returns a sentinel above every threshold so
    the guard fails SAFE (clears) rather than silently never firing."""
    try:
        try:
            from jax.extend.backend import get_backend
        except ImportError:  # older jax spells it via xla_bridge
            from jax.lib.xla_bridge import get_backend
        return len(get_backend().live_executables())
    except Exception:
        return 1 << 30


# --------------------------------------------------------------------------
# Simnet purity guard (round 10, extended round 13): the deterministic
# cluster lane (tests marked ``simnet``, over cluster/simnet.py) is only
# trustworthy if it genuinely never touches the wall clock or the real
# network — the moment one test quietly falls back to time.sleep or a
# loopback socket, its determinism claim is a lie and the lane rots back
# into the fragile timing tests it replaced.  The guard monkeypatches the
# escape hatches to raise AND records the violation, because a raise on a
# daemon thread (engine loop, heartbeat thread) dies silently — the
# teardown assert is what actually fails the test in that case.
#
# The banned-name list is IMPORTED from the static linter's manifest
# (analysis/manifest.py SIMNET_RUNTIME_BANNED), so the runtime lane and
# clockck enforce the same contract from one source: round 13 adds
# ``time.monotonic`` (a monotonic-paced busy-wait is a sleep by another
# name — code that holds a legitimately captured real clock, like
# simnet's settling waits or the engine's default clock, binds the
# function at import and is immune) and the ``select``/``selectors``-level
# escapes (socket IO and sleeping in one call, reachable without ever
# touching ``socket.socket``).
# --------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _simnet_purity_guard(request, monkeypatch):
    if request.node.get_closest_marker("simnet") is None:
        yield
        return
    import importlib
    import sys as sys_mod
    import time as time_mod
    import traceback

    # Must be imported BEFORE the patches land: simnet captures the real
    # monotonic clock at module import (its declared settling-wait seam);
    # a first import from inside a test body would capture the banned
    # wrapper instead.
    import distributed_sudoku_solver_tpu.cluster.simnet  # noqa: F401
    from distributed_sudoku_solver_tpu.analysis.manifest import (
        SIMNET_RUNTIME_BANNED,
    )

    violations: list[str] = []

    def _banned(what, passthrough=None):
        def call(*a, **k):
            if passthrough is not None:
                # Caller-scoped ban: jax's own dispatch internals read
                # time.monotonic (pjit cache-miss timing) on every real
                # device program a simnet test runs — that is not a
                # protocol-timing escape.  Only OUR frames (package,
                # tests) violate the contract.
                caller = sys_mod._getframe(1).f_globals.get("__name__", "")
                if not caller.startswith(
                    ("distributed_sudoku_solver_tpu", "tests", "__main__")
                ):
                    return passthrough(*a, **k)
            violations.append(
                f"{what}\n" + "".join(traceback.format_stack(limit=8))
            )
            raise AssertionError(f"simnet purity violation: {what}")

        return call

    real_monotonic = time_mod.monotonic
    for mod_name, attr in SIMNET_RUNTIME_BANNED:
        mod = importlib.import_module(mod_name)
        if hasattr(mod, attr):  # selectors vary by platform
            passthrough = (
                real_monotonic
                if (mod_name, attr) == ("time", "monotonic")
                else None
            )
            monkeypatch.setattr(
                mod, attr, _banned(f"{mod_name}.{attr}", passthrough)
            )
    yield
    assert not violations, "simnet purity violations:\n" + "\n".join(violations)


# --------------------------------------------------------------------------
# Lockdep witness (ISSUE 13): the runtime half of the deadck thread-plane
# contract, armed across the WHOLE tier-1 suite.  Every named lock
# acquisition is checked against the manifest hierarchy the moment it
# happens — a violating or cycle-forming acquisition raises in the thread
# that would have deadlocked — and is accumulated into one process-wide
# observed graph that tests/test_deadck.py cross-checks against deadck's
# predicted graph.  A raise on a daemon thread (device loop, heartbeat,
# handler) can be swallowed by that thread's catch-all, so the per-test
# guard below also asserts no NEW violations were recorded during the
# test — the simnet purity guard's record-and-raise pattern.
# --------------------------------------------------------------------------


@pytest.fixture(scope="session", autouse=True)
def lockdep_witness():
    from distributed_sudoku_solver_tpu.obs import lockdep

    witness = lockdep.manifest_witness(strict=True)
    lockdep.install(witness)
    yield witness
    lockdep.install(None)
    # The whole-suite cross-check (the acceptance twin of the explicit
    # test in tests/test_deadck.py, which can only see the tests that ran
    # BEFORE it): every edge observed across the entire session must be
    # in deadck's predicted graph.  Cheap (stdlib-ast, ~1 s) and failing
    # loudly at session end beats silently shipping a blind spot.
    from distributed_sudoku_solver_tpu.analysis.__main__ import run as _arun

    report, _ = _arun(rules=("deadck",))
    predicted = {tuple(e) for e in report["deadck"]["predicted"]}
    unpredicted = sorted(set(witness.graph()) - predicted)
    assert not unpredicted, (
        "tier-1 observed lock-order edges deadck did not predict "
        f"(fix the resolver or declare them): {unpredicted}"
    )


@pytest.fixture(autouse=True)
def _lockdep_violation_guard(lockdep_witness):
    before = len(lockdep_witness.violations)
    yield
    fresh = lockdep_witness.violations[before:]
    assert not fresh, (
        "lock-order violations recorded during this test:\n"
        + "\n".join(repr(v) for v in fresh)
    )


@pytest.fixture
def heavy_compile_guard():
    """Request this before any outsized XLA:CPU compile (see module note).

    Keyed on the resident-executable count, so it no-ops for isolated runs
    and early-suite positions, and clears exactly when the allocator
    pressure that correlates with the native-compiler segfault is present.
    """
    import jax

    if _resident_executable_count() > HEAVY_COMPILE_EXEC_THRESHOLD:
        jax.clear_caches()
    yield
