"""Test harness config: run everything on a virtual 8-device CPU mesh.

The TPU-world replacement for the reference's loopback-multiprocess testing
methodology (SURVEY.md §4): real shard_map collectives on fake devices.
Must run before jax is imported anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
