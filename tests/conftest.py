"""Test harness config: run everything on a virtual 8-device CPU mesh.

The TPU-world replacement for the reference's loopback-multiprocess testing
methodology (SURVEY.md §4): real shard_map collectives on fake devices.
Must run before jax initializes any backend; the heavy lifting (including
evicting an already-registered TPU-tunnel plugin) lives in
``utils/cpu_backend.py``.
"""

import os

# TPU lane (`TPU_TESTS=1 pytest -m tpu`): keep the real backend so the
# Pallas/Mosaic kernels compile on hardware instead of interpret mode —
# the regression net for lowering breakage (ROADMAP r1 #9).  Everything
# else runs on the virtual 8-device CPU mesh.
TPU_LANE = os.environ.get("TPU_TESTS") == "1"

if not TPU_LANE:
    # Env first, in case importing the package (below) is what first imports jax.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )

    from distributed_sudoku_solver_tpu.utils.cpu_backend import force_cpu_backend

    force_cpu_backend(n_devices=8)
