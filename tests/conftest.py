"""Test harness config: run everything on a virtual 8-device CPU mesh.

The TPU-world replacement for the reference's loopback-multiprocess testing
methodology (SURVEY.md §4): real shard_map collectives on fake devices.
Must run before jax initializes any backend.

Two layers of defense, because a TPU-tunnel plugin may already be
*registered* by the interpreter's sitecustomize before pytest imports us:
setting the env vars alone is not enough — the tunnel backend would still
be initialized (dialing out, and serializing on the tunnel) at the first
``jax.devices()``.  Dropping non-CPU backend factories keeps the suite
hermetic: pure in-process CPU, no device contention with concurrent
benchmark runs.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

try:
    import jax
    import jax._src.xla_bridge as _xb

    # sitecustomize may have imported jax already (capturing JAX_PLATFORMS
    # from the outer env), so update the live config, not just the env var.
    jax.config.update("jax_platforms", "cpu")
    for _name in list(_xb._backend_factories):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:  # pragma: no cover - plugin layout changed; env vars remain
    pass
