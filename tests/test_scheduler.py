"""Resident-flight scheduler tests (serving/scheduler.py): continuous
batching over one long-lived frontier.

Lifecycle coverage demanded by the round-7 issue: attach mid-flight, detach
on solve + slot reuse, cancel frees the slot in-graph, deadline expiry,
saturation -> 429 + Retry-After at the HTTP layer, and bit-equality of a
job's solution whether it ran in a static batch flight or the resident
flight.  Every engine here shares ONE SolverConfig / ResidentConfig shape
so the resident device programs (init / attach / detach / poll / advance)
compile once for the whole module.  The FIRST test — the one that triggers
those compiles — requests ``heavy_compile_guard``: the resident flight's
executables are persistent (they stay live for the engine's life and add
to the process's resident-executable census), so the guard gets one chance
to clear a crowded late-suite process BEFORE they land, and the census
they then inflate does not re-trip the guard on every later test here (a
per-test guard would clear_caches eight times in a row and force the rest
of the suite to re-load every program — measured as a multi-minute tier-1
regression).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.scheduler import (
    EngineSaturated,
    ResidentConfig,
    resident_solver_config,
)
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)
RC = ResidentConfig(
    job_slots=4, gang_lanes=4, queue_depth=32, attach_batch=4, chunk_steps=16
)


def wait_for(pred, timeout=30.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


def occupied(eng):
    m = eng.metrics().get("resident", {}).get("9x9")
    return m["occupied"] if m else 0


@pytest.fixture
def engine():
    eng = SolverEngine(config=SMALL, max_batch=8, resident=RC).start()
    yield eng
    eng.stop(timeout=2)


# -- frontier-op level: the in-graph attach/detach contract -------------------


def test_attach_detach_slot_reuse_and_gang_invariant(heavy_compile_guard):
    """Pure device-op lifecycle: attach two jobs into a live frontier,
    solve, detach one, attach a new tenant into the recycled slot — and
    gang-scoped stealing never leaks a job outside its slot's lanes."""
    import jax.numpy as jnp

    from distributed_sudoku_solver_tpu.ops.bitmask import decode_grid, encode_grid
    from distributed_sudoku_solver_tpu.ops.frontier import (
        attach_roots,
        detach,
        init_frontier_roots,
    )
    from distributed_sudoku_solver_tpu.ops.solve import finalize_frontier
    from distributed_sudoku_solver_tpu.utils.checkpoint import advance_frontier

    cfg = resident_solver_config(SMALL, SUDOKU_9, RC)
    gang, lanes = cfg.steal_gang, cfg.lanes
    assert lanes == RC.job_slots * gang
    st = init_frontier_roots(
        jnp.zeros((lanes, 9, 9), jnp.uint32),
        jnp.full(lanes, -1, jnp.int32),
        RC.job_slots,
        cfg,
    )
    grids = jnp.asarray(np.stack([EASY_9, HARD_9[0]]).astype(np.int32))
    st = attach_roots(
        st, encode_grid(grids, SUDOKU_9), jnp.asarray([0, 2], jnp.int32), gang
    )
    st = advance_frontier(st, jnp.int32(int(st.steps) + 500), SUDOKU_9, cfg)
    solved = np.asarray(st.solved)
    assert solved[0] and solved[2]
    sol2 = np.asarray(decode_grid(st.solution[2]))
    assert is_valid_solution(sol2)
    # Gang invariant: slot g's lanes only ever carry job g (or idle).
    jobs = np.asarray(st.job)
    for g in range(RC.job_slots):
        owners = set(jobs[g * gang : (g + 1) * gang].tolist()) - {-1}
        assert owners <= {g}, (g, owners)
    # Detach slot 0 and re-attach an unsat tenant into the recycled slot.
    st = detach(st, jnp.asarray([True, False, False, False]))
    assert np.asarray(st.job)[:gang].tolist() == [-1] * gang
    assert not np.asarray(st.solved)[0]
    bad = np.zeros((9, 9), np.int32)
    bad[0, 0] = bad[0, 1] = 5
    st = attach_roots(
        st,
        encode_grid(jnp.asarray(bad[None]), SUDOKU_9),
        jnp.asarray([0], jnp.int32),
        gang,
    )
    st = advance_frontier(st, jnp.int32(int(st.steps) + 500), SUDOKU_9, cfg)
    res = finalize_frontier(st)
    assert np.asarray(res.unsat)[0]  # recycled slot got a clean verdict
    assert np.asarray(res.solved)[2]  # the sitting tenant was untouched


# -- engine level -------------------------------------------------------------


def test_resident_serves_solved_and_unsat_and_recycles_slots(engine):
    """More jobs than slots: all resolve through slot recycling, solutions
    valid, unsat proven, and the flight drains to zero occupancy."""
    jobs = [engine.submit(p) for p in HARD_9] + [
        engine.submit(EASY_9) for _ in range(RC.job_slots)
    ]
    bad = np.zeros((9, 9), np.int32)
    bad[0, 0] = bad[0, 1] = 5
    ju = engine.submit(bad)
    for j in jobs:
        assert j.wait(120), j.error
        assert j.solved, (j.error, j.unsat)
        assert is_valid_solution(j.solution)
    assert ju.wait(120) and ju.unsat and not ju.solved
    m = engine.metrics()["resident"]["9x9"]
    assert m["admitted"] == len(jobs) + 1
    assert m["completed"] == len(jobs) + 1
    assert m["occupied"] == 0 and m["queued"] == 0
    assert engine.stats()["solved"] == len(jobs)
    assert engine.stats()["validations"] > 0


def test_resident_attach_mid_flight():
    """A job arriving while another is mid-search attaches to a free slot
    and finishes WITHOUT waiting for the sitting tenant to retire — the
    continuous-batching point."""
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        handicap_s=0.06,
        resident=ResidentConfig(
            job_slots=4, gang_lanes=4, queue_depth=8, attach_batch=4,
            chunk_steps=1,
        ),
    ).start()
    try:
        hard = eng.submit(HARD_9[1])
        assert wait_for(lambda: occupied(eng) >= 1, timeout=30)
        easy = eng.submit(EASY_9)
        assert easy.wait(30), "mid-flight arrival starved behind the tenant"
        assert easy.solved
        assert not hard.done.is_set(), (
            "hard tenant finished first — the handicap did not keep it busy "
            "long enough for the mid-flight assertion to mean anything"
        )
        assert hard.wait(120) and hard.solved
    finally:
        eng.stop(timeout=2)


def test_resident_cancel_frees_slot():
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        handicap_s=0.06,
        resident=ResidentConfig(
            job_slots=4, gang_lanes=4, queue_depth=8, attach_batch=4,
            chunk_steps=1,
        ),
    ).start()
    try:
        j = eng.submit(HARD_9[1])
        assert wait_for(lambda: occupied(eng) >= 1, timeout=30)
        eng.cancel(j.uuid)
        assert j.wait(30), "cancelled resident job must resolve promptly"
        assert j.cancelled and not j.solved and not j.unsat
        assert wait_for(lambda: occupied(eng) == 0, timeout=20)
        # The freed slot serves the next tenant.
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved
        assert eng.metrics()["resident"]["9x9"]["cancelled"] >= 1
    finally:
        eng.stop(timeout=2)


def test_resident_deadline_expiry_frees_slot():
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        handicap_s=0.06,
        resident=ResidentConfig(
            job_slots=4, gang_lanes=4, queue_depth=8, attach_batch=4,
            chunk_steps=1,
        ),
    ).start()
    try:
        # ~28 frontier steps at 0.06 s/chunk >> the 0.3 s deadline.
        j = eng.submit(HARD_9[1], deadline_s=0.3)
        assert j.wait(30)
        assert j.error == "deadline expired"
        assert not j.solved and not j.unsat
        assert wait_for(lambda: occupied(eng) == 0, timeout=20)
        assert eng.metrics()["resident"]["9x9"]["deadline_expired"] >= 1
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved, "slot not recycled after expiry"
    finally:
        eng.stop(timeout=2)


def test_cancelled_queued_job_resolves_without_free_slot():
    """A cancel landing on a job still WAITING in the admission queue must
    resolve it immediately — not when a slot happens to free — or a burst
    of timed-out clients would keep the bounded queue full of dead work,
    429-ing live traffic behind long-running tenants."""
    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        handicap_s=0.06,
        resident=ResidentConfig(
            job_slots=1, gang_lanes=4, queue_depth=4, attach_batch=1,
            chunk_steps=1,
        ),
    ).start()
    try:
        tenant = eng.submit(HARD_9[1])
        assert wait_for(lambda: occupied(eng) >= 1, timeout=30)
        queued = eng.submit(HARD_9[0])
        eng.cancel(queued.uuid)
        assert queued.wait(10), "dead queue entry stuck behind a busy slot pool"
        assert queued.cancelled and not queued.solved
        assert not tenant.done.is_set()  # no slot freed to make that happen
        assert tenant.wait(120) and tenant.solved
    finally:
        eng.stop(timeout=2)


def test_static_flight_deadline_expiry():
    """Deadlines are engine-wide: a job on the STATIC flight path (no
    resident flight configured) expires at chunk granularity too, so the
    wall-clock guarantee survives a resident-saturation fallback."""
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=1, handicap_s=0.06
    ).start()
    try:
        j = eng.submit(HARD_9[1], deadline_s=0.3)
        assert j.wait(30)
        assert j.error == "deadline expired"
        assert not j.solved and not j.unsat
        ok = eng.submit(EASY_9)
        assert ok.wait(60) and ok.solved, "loop died after deadline purge"
    finally:
        eng.stop(timeout=2)


def test_resident_bit_equal_to_static_flight(engine):
    """The acceptance bar: a job's solution is bit-identical whether it ran
    resident or in a static batch flight."""
    static = SolverEngine(config=SMALL, max_batch=8).start()  # no resident
    try:
        for board in HARD_9:
            jr = engine.submit(board)
            js = static.submit(board)
            assert jr.wait(120) and jr.solved, jr.error
            assert js.wait(120) and js.solved, js.error
            np.testing.assert_array_equal(jr.solution, js.solution)
    finally:
        static.stop(timeout=2)


def test_ineligible_jobs_fall_back_to_static_flights(engine):
    """Per-job config overrides (portfolio racers) and count_all submits
    keep the static path; the resident queue never sees them."""
    import dataclasses

    warm = engine.submit(EASY_9)  # instantiate the resident flight
    assert warm.wait(60) and warm.solved
    before = engine.metrics()["resident"]["9x9"]["admitted"]
    j = engine.submit(HARD_9[0], config=SMALL)  # explicit per-job config
    jc = engine.submit(
        np.zeros((4, 4), np.int32),
        config=dataclasses.replace(SMALL, count_all=True),
    )
    assert j.wait(120) and j.solved
    assert jc.wait(120) and jc.sol_count == 288  # empty 4x4: known count
    assert engine.metrics()["resident"]["9x9"].get("admitted", 0) == before


def test_saturation_rejects_and_http_429():
    """Slot pool + bounded queue full: library submits with
    saturation='reject' raise EngineSaturated, and the HTTP layer answers
    429 with a Retry-After header while admitted jobs still complete."""
    from distributed_sudoku_solver_tpu.serving.http import ApiServer, StandaloneNode

    eng = SolverEngine(
        config=SMALL,
        max_batch=8,
        handicap_s=0.03,
        resident=ResidentConfig(
            job_slots=1, gang_lanes=4, queue_depth=1, attach_batch=1,
            chunk_steps=1,
        ),
    ).start()
    node = StandaloneNode(engine=eng, address="127.0.0.1:test")
    api = ApiServer(node, host="127.0.0.1", port=0, solve_timeout_s=120).start()
    try:
        results = []
        lock = threading.Lock()

        def post():
            url = f"http://127.0.0.1:{api.port}/solve"
            body = json.dumps({"sudoku": np.asarray(HARD_9[1]).tolist()}).encode()
            req = urllib.request.Request(url, data=body, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    out = (resp.status, dict(resp.headers), json.loads(resp.read()))
            except urllib.error.HTTPError as e:
                out = (e.code, dict(e.headers), json.loads(e.read()))
            with lock:
                results.append(out)

        threads = [threading.Thread(target=post) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
            assert not t.is_alive()
        codes = sorted(c for c, _, _ in results)
        assert 429 in codes, codes
        assert 201 in codes, codes  # admitted jobs still served
        for code, headers, body in results:
            if code == 429:
                assert int(headers["Retry-After"]) >= 1
                assert body["retry_after_s"] > 0
        # Direct library-level reject surface.
        sat = eng.metrics()["resident"]["9x9"]["rejected"]
        assert sat >= 1
        with pytest.raises(EngineSaturated):
            for _ in range(8):
                eng.submit(HARD_9[1], saturation="reject")
        # Default policy quietly falls back to a static flight instead.
        jf = eng.submit(EASY_9)
        assert jf.wait(120) and jf.solved, jf.error
        # Observability rides GET /metrics: slot occupancy, admission
        # waits, and the rejects this storm produced, per geometry.
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/metrics", timeout=30
            ).read()
        )
        r = body["resident"]["9x9"]
        assert r["slots"] == 1
        assert {"occupied", "queued", "admitted"} <= set(r)
        assert r["rejected"] >= 1
        assert r["admission_wait_ms"]["count"] >= 1
    finally:
        api.stop()
        eng.stop(timeout=2)


def test_poisson_smoke_resident(engine):
    """Tier-1 smoke of the arrival-process benchmark harness: a small
    Poisson load fully resolves through the resident flight (the measured
    comparison lives in benchmarks/bench_poisson.py, marked slow below)."""
    from benchmarks.bench_poisson import poisson_load

    lats, jobs = poisson_load(
        engine, [np.asarray(p) for p in HARD_9] * 2, mean_gap_s=0.02, seed=3
    )
    assert len(lats) == len(jobs) == 6
    assert all(j.solved for j in jobs)
    assert all(lat > 0 for lat in lats)


@pytest.mark.slow
def test_poisson_resident_beats_static_p95():
    """The round-7 acceptance criterion, as a repeatable measurement: under
    Poisson arrivals with mean inter-arrival below the single-flight
    duration, the resident flight improves p95 time-to-solution over the
    static-flight baseline (numbers recorded in BENCHMARKS.md round 7)."""
    from benchmarks.bench_poisson import compare_poisson

    out = compare_poisson(n_jobs=24, mean_gap_s=0.05, handicap_s=0.05, seed=7)
    assert out["resident"]["p95_ms"] < out["static"]["p95_ms"], out


# -- satellite guards ---------------------------------------------------------


def test_cover_consts_rejects_sentinel_overflow():
    """ADVICE r5: instances whose argmin keys would reach the f32-exact
    _BIG sentinel must fail loudly in cover_consts, not corrupt branch
    selection silently."""
    from distributed_sudoku_solver_tpu.models.cover import ExactCoverCSP
    from distributed_sudoku_solver_tpu.ops.pallas_cover import cover_consts

    tiny = np.zeros((1, 1), np.uint32)
    big_rows = ExactCoverCSP(
        name="huge-rows",
        n_rows=1 << 21,
        n_primary=4,
        col_rows=tiny,
        row_cols=tiny,
        elim=tiny,
        incidence=tiny,
        n_cols_full=8,
    )
    with pytest.raises(ValueError, match="sentinel"):
        cover_consts(big_rows)
    big_pad = ExactCoverCSP(
        name="huge-pad",
        n_rows=4,
        n_primary=4,
        col_rows=tiny,
        row_cols=tiny,
        elim=np.zeros((1, 1 << 17), np.uint32),  # w_rows -> padded rows >= 1<<22
        incidence=tiny,
        n_cols_full=8,
    )
    with pytest.raises(ValueError, match="sentinel"):
        cover_consts(big_pad)


def test_cover_fused_lanes_vmem_admission():
    """ADVICE r5: an unservable (instance, stack) shape raises an
    actionable pre-compile error from cover_fused_lanes; served shapes
    (the whole shipped test fleet) stay admitted."""
    from distributed_sudoku_solver_tpu.models.nqueens import nqueens_cover
    from distributed_sudoku_solver_tpu.ops.pallas_cover import (
        cover_fused_lanes,
        cover_vmem_bytes,
    )

    p = nqueens_cover(8)
    assert cover_fused_lanes(64, p, 32) == 64  # shipped shape admitted
    assert cover_fused_lanes(200, p, 32) == 256  # rounding unchanged
    assert cover_vmem_bytes(p, 32) < 100 * 1024 * 1024
    with pytest.raises(ValueError, match="scoped VMEM"):
        cover_fused_lanes(64, p, stack_slots=200_000)
