"""Round-11 observability plane: flight-recorder tracing (obs/trace.py),
Prometheus exposition (obs/prom.py), the traceck validator, and log/uuid
correlation.

Three layers of assertions:

* **Recorder unit lane** — ring bound, link/resolve aliasing, per-uuid
  queries (primary id AND multi-job ``uuids`` attribution), ingest
  idempotence, Perfetto export validity, dump files.
* **Engine e2e lane** — a traced solve produces the full lifecycle
  (admission -> chunk dispatch/sync -> resolve), the disabled path records
  NOTHING (the zero-allocation guard-branch microcheck), and failure logs
  carry the job uuid.
* **Simnet acceptance** — a cluster solve with an injected permanent
  fault yields a stitched multi-node trace for the job's uuid, a
  flight-recorder dump containing the fault span, and Perfetto output
  that passes traceck — all on the virtual clock, no sleeps (the simnet
  marker guard enforces it).
"""

import json
import logging
import os

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.obs import prom, trace, traceck
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving import faults
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.faults import (
    FaultInjector,
    FaultSchedule,
)
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9, HARD_9

SMALL = SolverConfig(min_lanes=8, stack_slots=16)


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """Every test here must leave the process-wide seam clean."""
    yield
    trace.install(None)


# -- recorder unit lane --------------------------------------------------------


def test_ring_is_bounded_and_ordered():
    rec = trace.TraceRecorder(ring=32, clock=lambda: 1.0)
    for i in range(100):
        rec.event(f"u{i}", "e", "site")
    spans = rec.spans()
    assert len(spans) == 32
    assert spans[0]["trace"] == "u68" and spans[-1]["trace"] == "u99"


def test_link_resolve_and_uuid_attribution():
    t = 0.0
    rec = trace.TraceRecorder(clock=lambda: t)
    rec.link("root#p1", "root")
    rec.event("root", "resolve", "engine.resolve")
    rec.event("root#p1", "recv.SUBTASK", "cluster.recv")
    # A flight-level span attributes via its uuids list, not a primary id.
    rec.record(None, "chunk.dispatch", "engine.advance", 0.0,
               uuids=["root#p1", "other"])
    rec.event("other", "resolve", "engine.resolve")
    got = {s["name"] for s in rec.spans("root")}
    assert got == {"resolve", "recv.SUBTASK", "chunk.dispatch"}
    assert rec.resolve("root#p1") == "root"
    # Self-links and unknown uuids are harmless.
    rec.link("x", "x")
    assert rec.resolve("never-seen") == "never-seen"


def test_ingest_is_idempotent_and_defensive():
    rec = trace.TraceRecorder(clock=lambda: 0.0)
    span = rec.event("u1", "resolve", "engine.resolve")
    # Re-ingesting a span this recorder produced is a no-op (shared
    # recorder in the simnet lane); a genuinely remote span lands once.
    assert rec.ingest([dict(span)]) == 0
    remote = {
        "id": "peer/1", "trace": "u1", "name": "recv.TASK",
        "site": "cluster.recv", "t0": 0.0, "t1": 0.0, "node": "peer",
        "uuids": [], "attrs": {},
    }
    assert rec.ingest([dict(remote), dict(remote)]) == 1
    assert rec.remote_spans_ingested == 1
    # Garbage from the wire must be skipped, never raise.
    assert rec.ingest([None, 7, {"id": "x"}, {"no": "fields"}]) == 0
    assert rec.ingest("not a list") == 0
    assert len(rec.spans("u1")) == 2


def test_ingested_part_spans_resolve_into_root_trace():
    """Per-process recorders (any real cluster): the peer's spans for a
    shed part arrive with trace = the PART uuid and the peer's link table
    never crosses the wire — the shedder records the part->root link
    itself (_on_needwork / _on_part_result), so ingested part spans land
    in the root's stitched trace (review finding, round 11)."""
    rec = trace.TraceRecorder(clock=lambda: 0.0)
    rec.link("root#p1", "root")  # what the shedder records at shed time
    remote = {
        "id": "peer/9", "trace": "root#p1", "name": "resolve",
        "site": "engine.resolve", "t0": 0.0, "t1": 0.0, "node": "peer",
        "uuids": [], "attrs": {},
    }
    assert rec.ingest([remote]) == 1
    assert any(s["id"] == "peer/9" for s in rec.spans("root")), (
        "ingested part span missing from the root trace"
    )


def test_perfetto_export_passes_traceck_and_is_json():
    t = [0.0]
    rec = trace.TraceRecorder(clock=lambda: t[0])
    for i in range(5):
        t[0] = float(i)
        rec.record("u", f"s{i}", "engine.advance", float(i) - 0.5,
                   node=f"n{i % 2}")
    doc = rec.perfetto()
    assert traceck.check(doc) == []
    json.dumps(doc)  # JSON-native end to end
    # Two nodes -> two pids with process_name metadata.
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert len(pids) == 2


def test_traceck_rejects_malformed_documents():
    assert traceck.check([]) != []
    assert traceck.check({}) != []
    bad_ph = {"traceEvents": [{"name": "x", "ph": "Q", "pid": 1, "tid": 1}]}
    assert any("ph" in e for e in traceck.check(bad_ph))
    neg_dur = {"traceEvents": [
        {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": -5}
    ]}
    assert any("dur" in e for e in traceck.check(neg_dur))
    non_mono = {"traceEvents": [
        {"name": "a", "ph": "X", "pid": 1, "tid": 1, "ts": 10, "dur": 1},
        {"name": "b", "ph": "X", "pid": 1, "tid": 1, "ts": 5, "dur": 1},
    ]}
    assert any("monotone" in e for e in traceck.check(non_mono))


def test_traceck_cli_roundtrip(tmp_path):
    rec = trace.TraceRecorder(clock=lambda: 0.0)
    rec.event("u", "e", "s")
    good = tmp_path / "good.json"
    good.write_text(json.dumps(rec.perfetto()))
    assert traceck.main([str(good)]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    assert traceck.main([str(bad)]) == 1
    assert traceck.check_file(str(tmp_path / "missing.json")) != []
    # The *ck-family exit-code contract (obs/exitcodes.py): findings = 1,
    # but an input the tool cannot READ is the tool failing = 2.
    assert traceck.main([str(tmp_path / "missing.json")]) == 2
    assert traceck.main([]) == 2


def test_flight_recorder_dump_file(tmp_path):
    rec = trace.TraceRecorder(clock=lambda: 3.0, dump_dir=str(tmp_path),
                              dump_spans=2)
    for i in range(5):
        rec.event(f"u{i}", "e", "s")
    path = rec.dump("unit", metrics={"jobs_done": 1})
    doc = json.loads(open(path).read())
    assert doc["reason"] == "unit"
    assert len(doc["spans"]) == 2  # last dump_spans only
    assert doc["metrics"] == {"jobs_done": 1}
    assert rec.dumps == 1
    # No dump_dir -> disabled, never raises.
    assert trace.TraceRecorder().dump("x") is None


# -- engine e2e lane -----------------------------------------------------------


def test_traced_solve_records_full_lifecycle():
    rec = trace.TraceRecorder(ring=4096)
    with trace.installed(rec):
        eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
        try:
            j = eng.submit(HARD_9[1])
            assert j.wait(120) and j.solved, j.error
            m = eng.metrics()
        finally:
            eng.stop(timeout=2)
    names = {s["name"] for s in rec.spans(j.uuid)}
    assert {"admission", "chunk.dispatch", "resolve"} <= names, names
    adm = next(s for s in rec.spans(j.uuid) if s["name"] == "admission")
    assert adm["attrs"]["route"] == "static"
    assert adm["t1"] >= adm["t0"]  # the queue wait, on the recorder clock
    res = next(s for s in rec.spans(j.uuid) if s["name"] == "resolve")
    assert res["attrs"]["solved"] is True
    # Chunk spans ride the fault plane's site vocabulary.
    sites = {s["site"] for s in rec.spans(j.uuid)}
    assert "engine.advance" in sites
    # /metrics exposes recorder health while installed.
    assert m["trace"]["spans"] >= 3


def test_disabled_tracing_guard_branch_records_nothing(monkeypatch):
    """The zero-overhead microcheck: with no recorder installed, the
    instrumented hot loops must never construct or record a span — the
    guard is `trace.active() is None` and every allocation (uuid lists,
    clock reads, span dicts) lives behind it.  Monkeypatching the
    recording surface to explode proves the branch is never entered."""
    assert trace.active() is None

    def boom(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("span recorded while tracing is disabled")

    monkeypatch.setattr(trace.TraceRecorder, "record", boom)
    monkeypatch.setattr(trace.TraceRecorder, "event", boom)
    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
    try:
        j = eng.submit(HARD_9[1])
        assert j.wait(120) and j.solved, j.error
        assert j.trace_t0 is None  # not even the submit-time stamp
    finally:
        eng.stop(timeout=2)


def test_job_failure_logs_carry_uuid(caplog):
    """Log-correlation satellite: records about a failed job name its
    uuid, so a trace/HTTP uuid greps straight to the log evidence."""
    inj = FaultInjector(
        schedule=FaultSchedule.at({"engine.launch": {0: "permanent"}})
    )
    with faults.injected(inj):
        eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=2).start()
        try:
            with caplog.at_level(logging.ERROR):
                j = eng.submit(EASY_9)
                assert j.wait(120)
                assert j.error and "[permanent]" in j.error
        finally:
            eng.stop(timeout=2)
    assert any(
        j.uuid in r.getMessage() for r in caplog.records
    ), "no log record carries the failed job's uuid"


def test_breaker_open_transition_traces_and_dumps(tmp_path):
    """The other flight-recorder moment: consecutive resident rebuild
    failures drive the breaker open — the transition is a trace event and
    triggers an automatic dump (host-side only: the flight never touches
    the device here)."""
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.serving.scheduler import ResidentConfig

    t = [0.0]
    rec = trace.TraceRecorder(
        clock=lambda: t[0], dump_dir=str(tmp_path)
    )
    with trace.installed(rec):
        eng = SolverEngine(
            config=SMALL,
            resident=ResidentConfig(job_slots=2, gang_lanes=4),
            recovery=faults.RecoveryPolicy(
                breaker_failures=2, clock=lambda: t[0]
            ),
        )
        rf = eng._resident_for(SUDOKU_9)
        assert rf is not None
        rf.on_failure(RuntimeError("UNAVAILABLE: preempted (simulated)"))
        assert rf.breaker.state == rf.breaker.CLOSED
        rf.on_failure(RuntimeError("UNAVAILABLE: preempted (simulated)"))
        assert rf.breaker.state == rf.breaker.OPEN
    transitions = [s for s in rec.spans() if s["name"] == "breaker"]
    assert transitions and transitions[-1]["attrs"]["to"] == "open"
    dumps = [f for f in os.listdir(tmp_path) if "breaker_open" in f]
    assert dumps, "breaker-open transition must write a flight-recorder dump"
    doc = json.loads((tmp_path / dumps[0]).read_text())
    assert doc["reason"] == "breaker_open"
    assert doc["metrics"]["resident"]["9x9"]["faults"]["rebuilds"] >= 1


# -- prometheus exposition -----------------------------------------------------

# A fixed metrics-shaped dict covering every flattening rule: nested
# windows, geometry dicts, method-label dicts, string leaves (breaker
# state, device info), numeric lists (histogram buckets, the view pair),
# bools, and skipped None/empty values.
PROM_SAMPLE = {
    "jobs_done": 42,
    "solved": 40,
    "job_latency_ms": {"count": 10, "p50": 1.5, "p95": 20.25},
    "resident": {
        # Round-21 mesh section (serving/mesh_scheduler): per-shard gauges
        # render as indexed numeric-list series, counters as plain leaves.
        "9x9": {
            "occupied": 3,
            "queued": 0,
            "mesh": {
                "devices": 4,
                "slot_occupancy": [2, 1, 0, 0],
                "shard_live_lanes": [6, 3, 1, 0],
                "shard_foreign_lanes": [0, 2, 1, 0],
                "ring_shipped": 61,
                "rebuilds": 1,
            },
        },
        "16x16": {"occupied": 1, "queued": 2},
    },
    "faults": {
        "retries": 7,
        "breaker": {"9x9": {"state": "half_open", "transitions": 3}},
    },
    "cluster": {
        "address": "10.0.0.1:7000",
        "view": [1, 4],
        "faults": {
            "duplicates_dropped": {"SOLUTION": 2, "TASK": 1},
            # Round-20 partition-survival counters: result sends parked
            # after budget exhaustion, and their late re-deliveries.
            "results_parked": 1,
            "results_delivered_late": 1,
        },
    },
    # Round-20 DHT plane (cluster/dht): gossip liveness counters, ring
    # shape, the node's cluster-cache shard, and cache-affine routing —
    # all plain counters/gauges (no new label dicts), rolled up across
    # members by obs/agg._merge_dht.
    "dht": {
        "gossip": {
            "alive": 3,
            "suspect": 1,
            "dead": 0,
            "incarnation": 2,
            "refutations": 1,
            "suspicions": 2,
            "deaths": 0,
            "resurrections": 0,
            "stale_ignored": 4,
            "merged": 57,
        },
        "ring": {"members": 3, "vnodes": 32},
        "cluster_cache": {
            "entries": 4,
            "capacity": 65536,
            "lookups": 21,
            "local_hits": 6,
            "remote_hits": 9,
            "negative_hits": 1,
            "misses": 5,
            "remote_errors": 1,
            "puts_sent": 7,
            "puts_failed": 1,
            "puts_applied": 5,
            "gets_served": 14,
            "insertions": 9,
            "evictions": 0,
        },
        "affinity": {"routed": 11, "declined": 2},
    },
    "fused_lane_occupancy": {"counts": [5, 0, 9], "mean_pct": 61.5},
    "device": {"kind": "cpu", "platform": "cpu"},
    "healthy": True,
    "nothing": None,
    "empty": {},
    # Round-12 cluster-scope sections: a mergeable log2 histogram (renders
    # as cumulative le buckets + _sum/_count; exemplars stay JSON-only),
    # the rpc-floor estimate, and the SLO plane (objectives label dict).
    "hist": {
        "solve_ms": {
            "type": "log2_hist",
            "edge0_ms": 0.001,
            "counts": [0] * 10 + [3, 1] + [0] * 19 + [1],
            "sum_ms": 3105.2,
            "exemplars": {"11": "1f2e3d4c"},
        },
        # A front-door per-route latency histogram (round 17): rides the
        # same mergeable keyspace as every other phase histogram.
        "frontdoor_cache_ms": {
            "type": "log2_hist",
            "edge0_ms": 0.001,
            "counts": [0] * 11 + [7, 5] + [0] * 19,
            "sum_ms": 51.75,
        },
        # Round-19 whole-flight megastep latency: exactly ONE sample per
        # flight (the one-sync-per-flight proof rides this count).
        "frontdoor_megastep_ms": {
            "type": "log2_hist",
            "edge0_ms": 0.001,
            "counts": [0] * 16 + [30, 19] + [0] * 14,
            "sum_ms": 3917.4,
        },
    },
    "rpc_floor_ms": {"type": "min_est", "min": 48.9, "recent": 50.2,
                     "samples": 210},
    "slo": {
        "burn_threshold": 1.0,
        "window_s": 60,
        "burning": False,
        "burns": 1,
        "dumps": 1,
        "objectives": {
            "solve_p95_ms<=250": {
                "stream": "solve",
                "budget": 0.05,
                "threshold": 250.0,
                "burn_rate": 0.4,
                "burning": False,
                "breaches": 1,
                "window_total": 100,
                "window_bad": 2,
            },
        },
    },
    # Round-18 brownout section (serving/brownout.py): stage gauge,
    # transition counters, per-tier shed counts as a `tier`-labeled
    # table, residency/entered vectors (index label), and the last
    # evaluated pressure readings.
    "brownout": {
        "stage": 1,
        "enter": 1.0,
        "exit": 0.5,
        "quiet_s": 15.0,
        "transitions": 3,
        "escalations": 2,
        "deescalations": 1,
        "stage_entered": [0, 2, 1, 0],
        "stage_residency_s": [42.5, 3.25, 1.5, 0.0],
        "shed_total": 4,
        "shed": {"easy": 3, "hard": 1},
        "shed_by_stage": [0, 0, 3, 1],
        "pressure": {"burn": 1.31, "queue": 0.25, "wait": 0.1,
                     "floor": 0.27},
    },
    # Round-15 sections: the compile watch (per-program counts/walls as
    # a `program`-labeled table + alarm state), the cost plane (per-
    # program flops/bytes + the efficiency gauge), and critical-path
    # attribution (per-phase totals/shares; its histograms ride `hist`).
    "compile": {
        "programs": {
            "advance_status": {
                "count": 1,
                "wall_ms_total": 1812.4,
                "wall_ms": {
                    "type": "log2_hist",
                    "edge0_ms": 0.001,
                    "counts": [0] * 21 + [1] + [0] * 10,
                    "sum_ms": 1812.4,
                },
            },
            "unregistered": {"count": 3, "wall_ms_total": 40.25},
        },
        "registered": 29,
        "compiles_total": 4,
        "recompiles_total": 0,
        "warmup_over": True,
        "armed": True,
        "dumps": 0,
        "cache": {"persistent_cache_hits": 2, "persistent_cache_misses": 1},
    },
    "cost": {
        "programs": {
            "advance_status": {
                "flops": 60774.0,
                "bytes_accessed": 1147547.0,
                "geometry": "9x9",
                "lanes": 8,
                "chunk_steps": 64,
            },
        },
        "efficiency": {
            "program": "advance_status",
            "flops_per_round": 60774.0,
            "achieved_rounds_per_s": 771.996,
            "achieved_gflops_per_s": 0.046917,
        },
    },
    # Round-17 front-door section (serving/frontdoor): route counters as
    # a `route`-labeled table, cache hit/miss/eviction/canonical-dup
    # counters, probe verdicts, and the availability/fallback gauges.
    "frontdoor": {
        "routes": {"cache": 12, "propagation": 30, "native": 5, "device": 3},
        "probe": {"solved": 28, "unsat": 2, "easy": 5, "hard": 3},
        "uncacheable": 1,
        "native_available": True,
        "native_fallback_wins": 0,
        "pending_fills": 2,
        "cache": {
            "entries": 4,
            "capacity": 65536,
            "hits": 12,
            "negative_hits": 1,
            "misses": 38,
            "evictions": 0,
            "insertions": 9,
            "canonical_dups": 9,
        },
    },
    # Round-19 serving-megastep section (serving/megastep.py): per-
    # geometry flight counters with the nested degrade taxonomy, the
    # chunks-per-flight gauge, the whole-flight wall window, and the
    # flight breaker's string-state leaf — plus the engine-level
    # unfit-gang-shape counter.
    "megastep": {
        "9x9": {
            "gang_lanes": 8,
            "chunk_steps": 64,
            "max_chunks": 64,
            "flights": 49,
            "solved": 49,
            "unsat": 0,
            "degraded": {
                "budget": 0,
                "overflow": 0,
                "fault": 0,
                "breaker": 0,
            },
            "chunks_total": 49,
            "chunks_per_flight": 1.0,
            "flight_wall_ms": {"count": 49, "p50": 68.096, "p95": 92.355},
            "breaker": {
                "state": "closed",
                "consecutive_failures": 0,
                "transitions": 0,
            },
        },
    },
    "megastep_unfit": 1,
    # ISSUE-20 durability plane: WAL health (the `durable` boolean renders
    # 1.0/0.0) and the drain-ladder lifecycle — `state` is already numeric
    # at the source (0=serving 1=draining 2=drained) so the Prometheus
    # plane needs no string mapping.
    "journal": {
        "durable": True,
        "accepted": 42,
        "resolved": 40,
        "recovered": 3,
        "unresolved": 2,
        "pending": 1,
        "append_failures": 0,
        "fsync_failures": 0,
        "dropped_non_durable": 0,
        "compactions": 2,
        "segments_removed": 2,
        "segment_index": 3,
        "fsync_interval_s": 0.05,
    },
    "lifecycle": {
        "state": 0,
        "drain_handoffs": 3,
        "drain_journaled": 1,
        "drain_finished": 2,
        "recovered_jobs": 3,
        "resubmit_registry": 5,
    },
    "critpath": {
        "jobs": 12,
        "attribution_ms": {
            "sync": 820.5,
            "event": 14.0,
            "dispatch": 95.25,
            "wire": 0.0,
            "recovery": 0.0,
            "queue": 310.0,
            "other": 60.25,
        },
        "shares_pct": {
            "sync": 63.15,
            "event": 1.08,
            "dispatch": 7.33,
            "wire": 0.0,
            "recovery": 0.0,
            "queue": 23.86,
            "other": 4.64,
        },
        "slow_jobs": 1,
        "slow_dumps": 1,
        "threshold_ms": 250.0,
    },
}

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "prometheus_golden.txt")


def test_prometheus_render_matches_golden_file():
    got = prom.render(PROM_SAMPLE)
    want = open(GOLDEN).read()
    assert got == want, (
        "prometheus exposition drifted from the golden file; if the change "
        "is deliberate, regenerate tests/data/prometheus_golden.txt"
    )


def test_prometheus_render_escapes_and_shapes():
    out = prom.render({"s": 'a"b\\c\nd', "n": 1.25})
    assert 'dsst_s{s="a\\"b\\\\c\\nd"} 1' in out
    assert "dsst_n 1.25" in out
    assert out.endswith("\n")
    assert prom.render({}) == ""


def test_prometheus_sample_passes_promck():
    """The renderer and the lint agree on the whole rule surface: the
    golden sample (every flattening rule incl. the histogram/SLO series)
    must come out the other side clean."""
    from distributed_sudoku_solver_tpu.obs import promck

    assert promck.check_text(prom.render(PROM_SAMPLE)) == []


def test_promck_over_live_prometheus_endpoint():
    """Satellite: the LIVE ``GET /metrics?format=prometheus`` body — with
    the histogram sections populated by a real solve, the round-15
    compile/cost/critpath planes installed, AND the round-17 front door
    routing real traffic (a device-routed hard board, a propagation-
    answered easy board, and a symmetry-transformed cache hit), AND the
    round-19 latency mode flying the device-routed board on a real
    megastep — passes promck and carries the frontdoor + megastep
    families."""
    import urllib.request

    import numpy as np

    from distributed_sudoku_solver_tpu.obs import compilewatch, critpath, promck
    from distributed_sudoku_solver_tpu.serving.frontdoor.canonical import (
        apply_transform,
        random_transform,
    )
    from distributed_sudoku_solver_tpu.serving.frontdoor.router import (
        FrontDoorConfig,
    )
    from distributed_sudoku_solver_tpu.serving.http import (
        ApiServer,
        StandaloneNode,
    )
    from distributed_sudoku_solver_tpu.serving.megastep import MegastepConfig
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

    from distributed_sudoku_solver_tpu.serving import brownout

    rec = trace.TraceRecorder(ring=4096)
    watch = compilewatch.CompileWatch(warmup_s=3600.0)
    mon = critpath.CritPathMonitor()
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=4,
        frontdoor=FrontDoorConfig(), latency_mode=True,
        megastep=MegastepConfig(gang_lanes=8, chunk_steps=4, max_chunks=64),
    ).start()
    ctrl = brownout.BrownoutController()
    brownout.bind_engine(ctrl, eng)
    api = ApiServer(StandaloneNode(eng), host="127.0.0.1", port=0).start()
    try:
        with trace.installed(rec), compilewatch.installed(watch), \
                critpath.installed(mon), brownout.installed(ctrl):
            j = eng.submit(HARD_9[1])  # hard tail: device route
            assert j.wait(120) and j.solved, j.error
            je = eng.submit(np.asarray(EASY_9))  # propagation route
            assert je.wait(30) and je.solved and je.route == "propagation"
            transformed = apply_transform(
                HARD_9[1], random_transform(SUDOKU_9, np.random.default_rng(5))
            )
            jc = eng.submit(transformed)  # symmetry-canonical cache hit
            assert jc.wait(30) and jc.solved and jc.route == "cache"
            # A per-request latency OPT-OUT on a latency-mode engine:
            # this hard board takes the CHUNKED device path, which is
            # what feeds the rpc_floor estimator (the megastep's single
            # whole-flight sync never does — by contract).
            jk = eng.submit(HARD_9[0], latency=False)
            assert jk.wait(120) and jk.solved, jk.error
            raw = (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{api.port}/metrics?format=prometheus",
                    timeout=30,
                )
                .read()
                .decode()
            )
    finally:
        api.stop()
        eng.stop(timeout=2)
    assert promck.check_text(raw) == [], promck.check_text(raw)[:5]
    # The histogram plane is live: cumulative buckets ending at +Inf.
    assert 'dsst_hist_latency_ms_bucket{le="+Inf"}' in raw
    assert "dsst_hist_latency_ms_count" in raw
    assert "dsst_rpc_floor_ms_min" in raw
    # Round-15 families render and lint: compile counts label by
    # program, the cost plane's efficiency gauge is live, and the
    # critical-path histograms joined the mergeable hist keyspace.
    assert "dsst_compile_compiles_total" in raw
    assert "dsst_compile_registered 29" in raw
    assert 'dsst_cost_programs_flops{program="advance_status"}' in raw
    assert "dsst_cost_efficiency_achieved_gflops_per_s" in raw
    assert "dsst_critpath_jobs" in raw
    assert 'dsst_hist_critpath_sync_ms_bucket{le="+Inf"}' in raw
    # Round-17 front-door families: route counters under the `route`
    # label, cache counters (the transformed resubmit is both a hit and
    # a canonical dup), and the per-route latency histograms in `hist`.
    assert 'dsst_frontdoor_routes{route="device"} 2' in raw
    assert 'dsst_frontdoor_routes{route="cache"} 1' in raw
    assert 'dsst_frontdoor_routes{route="propagation"} 1' in raw
    assert "dsst_frontdoor_cache_hits 1" in raw
    assert "dsst_frontdoor_cache_canonical_dups 1" in raw
    assert 'dsst_hist_frontdoor_cache_ms_bucket{le="+Inf"} 1' in raw
    assert 'dsst_hist_frontdoor_device_ms_bucket{le="+Inf"} 2' in raw
    # Round-19 megastep families: the hard board flew on the megastep
    # (the front door still counted it as route=device — latency mode
    # changes the DISPATCH, not the routing verdict), its one sync is
    # the single whole-flight histogram sample, and the flight breaker's
    # string state renders as an info-style gauge.
    assert 'dsst_megastep_flights{geometry="9x9"} 1' in raw
    assert 'dsst_megastep_solved{geometry="9x9"} 1' in raw
    assert 'dsst_megastep_degraded_budget{geometry="9x9"} 0' in raw
    assert 'dsst_megastep_breaker_state{geometry="9x9",state="closed"} 1' in raw
    assert 'dsst_hist_frontdoor_megastep_ms_bucket{le="+Inf"} 1' in raw
    assert "dsst_hist_frontdoor_megastep_ms_count 1" in raw
    # Round-18 brownout families (serving/brownout.py): the stage gauge,
    # the tier-labeled shed table, and the transition counters render
    # from the LIVE controller (healthy here: stage 0, nothing shed).
    assert "dsst_brownout_stage 0" in raw
    assert 'dsst_brownout_shed{tier="easy"} 0' in raw
    assert 'dsst_brownout_shed{tier="hard"} 0' in raw
    assert "dsst_brownout_transitions 0" in raw


# -- simnet acceptance ---------------------------------------------------------


@pytest.mark.simnet
def test_cluster_trace_stitching_fault_dump_and_perfetto(tmp_path):
    """Acceptance: a simnet cluster solve with an injected PERMANENT fault
    produces (1) a stitched multi-node trace for the job's uuid — spans
    recorded by both the origin and the worker, trace context having
    ridden the TASK/SOLUTION frames; (2) an automatic flight-recorder
    dump containing the fault span; (3) Perfetto export that passes the
    traceck validator.  Everything timestamps through the simnet virtual
    clock (the recorder's injected clock), and the simnet marker guard
    proves no sleeps/sockets."""
    from distributed_sudoku_solver_tpu.cluster.node import (
        ClusterConfig,
        ClusterNode,
    )
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until

    from tests.test_cluster import oracle_solve_fn

    cfg = ClusterConfig(
        heartbeat_s=0.25, fail_factor=8.0, io_timeout_s=2.0, needwork=False,
        progress_interval_s=0.0, retry_delay_s=0.1, tombstone_probe_s=600.0,
    )
    net = SimNet()
    rec = trace.TraceRecorder(
        ring=8192, clock=net.clock.now, node="driver", dump_dir=str(tmp_path)
    )
    # engine.launch #0 is the worker's first (and only) flight launch:
    # the poison dispatch a retry cannot cure.
    inj = FaultInjector(
        schedule=FaultSchedule.at({"engine.launch": {0: "permanent"}})
    )
    ea = eb = a = b = None
    try:
        with trace.installed(rec), faults.injected(inj):
            ea = SolverEngine(
                solve_fn=oracle_solve_fn(), batch_window_s=0.001
            ).start()
            eb = SolverEngine(
                config=SolverConfig(min_lanes=4, stack_slots=32, branch="first"),
                chunk_steps=1,
                batch_window_s=0.001,
            ).start()
            a = ClusterNode(ea, config=cfg, transport=net.transport(),
                            clock=net.clock).start()
            b = ClusterNode(eb, anchor=a.addr, config=cfg,
                            transport=net.transport(), clock=net.clock).start()
            assert wait_until(
                net, lambda: len(a.network) == 2 and len(b.network) == 2,
                timeout=60,
            ), "ring never formed"
            job = a._submit_remote(np.asarray(EASY_9, np.int32), b.addr_s)
            assert wait_until(net, lambda: job.done.is_set(), timeout=240), (
                "remote job never resolved"
            )
            assert job.error and "[permanent]" in job.error

            # (1) Stitched multi-node trace: the one uuid reconstructs the
            # whole distributed story, each span tagged with its recorder.
            spans = rec.spans(job.uuid)
            names = {s["name"] for s in spans}
            assert {"send.TASK", "recv.TASK", "admission",
                    "fault.permanent", "send.SOLUTION",
                    "recv.SOLUTION"} <= names, names
            span_nodes = {s["node"] for s in spans}
            assert {a.addr_s, b.addr_s} <= span_nodes, (
                f"trace not stitched across nodes: {span_nodes}"
            )
            # Timestamps ride the virtual clock: nothing precedes t=0 and
            # every span is monotone.
            assert all(0.0 <= s["t0"] <= s["t1"] for s in spans)

            # (2) The flight-recorder dump fired on the permanent fault
            # and holds the fault span for this uuid.  The dump is written
            # on the worker's device loop, concurrently with the SOLUTION
            # round-trip that resolved the handle — wait for the file, on
            # the virtual clock (wait_until yields real scheduler slices).
            assert wait_until(
                net,
                lambda: any(
                    f.endswith("permanent_fault.json")
                    for f in os.listdir(tmp_path)
                ),
                timeout=60,
            ), "no flight-recorder dump on the permanent fault"
            dumps = [f for f in os.listdir(tmp_path)
                     if f.endswith("permanent_fault.json")]
            doc = json.loads((tmp_path / dumps[0]).read_text())
            assert any(
                s["name"] == "fault.permanent" and s["trace"] == job.uuid
                for s in doc["spans"]
            )
            assert doc["metrics"]["faults"]["permanent_failures"] >= 1

            # (3) GET /trace?format=perfetto serves exactly this payload
            # (serving/http.py delegates to rec.perfetto()): it must pass
            # the traceck validator.
            assert traceck.check(rec.perfetto()) == []
    finally:
        for n in (a, b):
            if n is not None:
                n.kill()
        for e in (ea, eb):
            if e is not None:
                e.stop(timeout=1)
        net.close()


@pytest.mark.simnet
def test_promck_over_live_gossip_node():
    """Satellite (round 20): the prometheus body of a LIVE gossip member —
    a 3-node simnet ring with the DHT plane on, a cross-member cache hit
    behind it — passes promck and carries the dsst_dht_* families (gossip
    liveness, ring shape, cluster-cache shard, affinity counters) plus
    the round-20 partition-survival fault counters."""
    from distributed_sudoku_solver_tpu.obs import promck
    from distributed_sudoku_solver_tpu.cluster.simnet import SimNet, wait_until
    from tests.test_dht import _dht_ring, _digest_of, _owner_node

    net = SimNet()
    net.nodes = []
    try:
        nodes, _calls = _dht_ring(net, 3)
        a = nodes[0]
        board = np.asarray(HARD_9[0], np.int32)
        j = a.engine.submit(board)
        assert j.wait(60) and j.solved, j.error
        owner = _owner_node(nodes, _digest_of(board))
        assert wait_until(net, lambda: len(owner.dcache) >= 1, timeout=30)
        requester = next(n for n in nodes if n is not a and n is not owner)
        j2 = requester.engine.submit(board)
        assert j2.wait(60) and j2.solved and j2.route == "cache"

        for member in (requester, owner):
            raw = prom.render(member.metrics_view())
            assert promck.check_text(raw) == [], promck.check_text(raw)[:5]
            assert "dsst_dht_gossip_alive 3" in raw
            assert "dsst_dht_ring_members 3" in raw
            assert "dsst_dht_cluster_cache_capacity" in raw
            assert "dsst_dht_affinity_routed" in raw
            assert "dsst_cluster_faults_results_parked 0" in raw
        assert (
            "dsst_dht_cluster_cache_remote_hits 1"
            in prom.render(requester.metrics_view())
        )
        # The owner served at least the requester's GET (A's warm-up
        # lookup may have landed there too — don't pin the count).
        assert owner.dcache.metrics()["gets_served"] >= 1
        assert "dsst_dht_cluster_cache_gets_served" in prom.render(
            owner.metrics_view()
        )
    finally:
        for n in net.nodes:
            n.kill()
            n.engine.stop(timeout=1)
        net.close()
