"""Brownout controller (serving/brownout.py, ISSUE 15): the stage ladder
on a fake clock, shedding through the real front door, the shed-response
HTTP contract, and the disabled-path microcheck.

Lanes:

* **unit** — hysteresis enter/exit thresholds, exactly-once transitions,
  re-arm after a quiet window, the gate policy matrix, broken signals.
* **engine** — stage 2/3 shedding through ``SolverEngine`` + the front
  door (reject vs quiet-fallback submits), stage-1 native-only (the
  device shadow provably suppressed), the 504-storm e2e overload walk.
* **http** — machine-readable shed bodies, Retry-After, and the pin that
  shed responses never burn the error-rate objective they protect.
* **microcheck** — with no controller installed the serving path never
  touches the controller surface (one global read + branch).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
from distributed_sudoku_solver_tpu.obs import slo
from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
from distributed_sudoku_solver_tpu.serving import brownout
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.serving.frontdoor.router import FrontDoorConfig
from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, make_puzzle

SMALL = SolverConfig(min_lanes=8, stack_slots=24, max_steps=40_000)

#: Probe-open, easy-scored boards (pinned by test_frontdoor's probe
#: classification lane): seeds whose 30-clue puzzles stay open after
#: propagation with branching slack under the default easy threshold.
EASY_OPEN_SEEDS = (123, 148, 151, 152, 155, 156, 186)


def _easy_open(i: int = 0) -> np.ndarray:
    return make_puzzle(SUDOKU_9, seed=EASY_OPEN_SEEDS[i], n_clues=30)


class FakeClock:
    """Injectable clock: the ladder advances when the TEST says so."""

    def __init__(self, t0: float = 1000.0):
        self.t = t0
        self._lock = threading.Lock()

    def __call__(self) -> float:
        with self._lock:
            return self.t

    def advance(self, dt: float) -> None:
        with self._lock:
            self.t += dt


def _ctrl(clock, press, **cfg_kw):
    defaults = dict(enter=1.0, exit=0.5, quiet_s=5.0, hold_s=1.0,
                    eval_interval_s=0.0)
    defaults.update(cfg_kw)
    return brownout.BrownoutController(
        brownout.BrownoutConfig(**defaults),
        clock=clock,
        signals={"burn": lambda: press[0]},
    )


# -- unit lane: the ladder on a fake clock -------------------------------------


def test_config_rejects_inverted_hysteresis_band():
    with pytest.raises(ValueError):
        brownout.BrownoutConfig(enter=1.0, exit=1.0)
    with pytest.raises(ValueError):
        brownout.BrownoutConfig(enter=0.5, exit=0.8)


def test_ladder_escalates_one_stage_per_hold_window():
    clock, press = FakeClock(), [2.0]
    ctrl = _ctrl(clock, press, hold_s=1.0)
    assert ctrl.evaluate() == 1  # first crossing climbs immediately
    # Inside the hold window: pressure stays high but the ladder dwells.
    clock.advance(0.5)
    assert ctrl.evaluate() == 1
    clock.advance(0.6)
    assert ctrl.evaluate() == 2
    clock.advance(1.1)
    assert ctrl.evaluate() == 3
    # MAX_STAGE is the ceiling however long the storm lasts.
    clock.advance(10.0)
    assert ctrl.evaluate() == 3
    assert ctrl.escalations == 3 and ctrl.transitions == 3
    assert ctrl.stage_entered == [0, 1, 1, 1]


def test_ladder_hysteresis_band_neither_climbs_nor_calms():
    clock, press = FakeClock(), [2.0]
    ctrl = _ctrl(clock, press, quiet_s=2.0)
    ctrl.evaluate()
    assert ctrl.stage() == 1
    # Pressure drops into the band (exit < p < enter): stage holds and no
    # calm accrues, however long it sits there.
    press[0] = 0.75
    for _ in range(10):
        clock.advance(5.0)
        assert ctrl.evaluate() == 1
    # Only genuinely-calm readings de-escalate, and only after quiet_s of
    # UNBROKEN calm — a band excursion resets the calm window.
    press[0] = 0.2
    clock.advance(1.0)
    assert ctrl.evaluate() == 1  # calm just started
    press[0] = 0.75
    clock.advance(1.5)
    assert ctrl.evaluate() == 1  # band visit wipes the accrued calm
    press[0] = 0.2
    clock.advance(1.0)
    assert ctrl.evaluate() == 1
    clock.advance(2.1)
    assert ctrl.evaluate() == 0
    assert ctrl.deescalations == 1 and ctrl.transitions == 2


def test_ladder_full_cycle_counts_exactly_once_and_rearms():
    clock, press = FakeClock(), [1.5]
    ctrl = _ctrl(clock, press, hold_s=0.5, quiet_s=2.0)
    for _ in range(5):
        ctrl.evaluate()
        clock.advance(0.6)
    assert ctrl.stage() == 3
    press[0] = 0.0
    for _ in range(5):
        clock.advance(2.1)
        ctrl.evaluate()
    assert ctrl.stage() == 0
    assert ctrl.transitions == 6
    assert ctrl.escalations == 3 and ctrl.deescalations == 3
    # Re-arm: a second storm climbs the ladder again — fresh transitions,
    # not a saturated one-shot alarm.
    press[0] = 1.5
    for _ in range(5):
        ctrl.evaluate()
        clock.advance(0.6)
    assert ctrl.stage() == 3 and ctrl.escalations == 6
    m = ctrl.metrics()
    assert m["transitions"] == 9
    # Both directions "enter" a stage: 2 storms x (1,2,3) + one walk-down
    # through (2,1,0).
    assert m["stage_entered"] == [1, 3, 3, 2]
    assert sum(m["stage_residency_s"]) == pytest.approx(
        clock() - 1000.0, abs=1e-6
    )


def test_gate_policy_matrix():
    clock, press = FakeClock(), [2.0]
    ctrl = _ctrl(clock, press, hold_s=0.0)
    # Freeze evaluation so gate() reads a pinned stage per row.
    expected = {
        0: {"easy": brownout.SERVE, "hard": brownout.SERVE},
        1: {"easy": brownout.NATIVE_ONLY, "hard": brownout.SERVE},
        2: {"easy": brownout.SHED, "hard": brownout.SERVE},
        3: {"easy": brownout.SHED, "hard": brownout.SHED},
    }
    press[0] = 0.75  # hysteresis band: stage frozen between forced climbs
    for stage in range(4):
        for tier, want in expected[stage].items():
            action, got_stage = ctrl.gate(tier)
            assert (action, got_stage) == (want, stage), (stage, tier)
        if stage < 3:
            press[0] = 2.0
            ctrl.evaluate()
            press[0] = 0.75
    # Shed statuses: 503 only at stage 2, 429 at stage 3.
    assert brownout.BrownoutShed(2, 1.0, "easy").status == 503
    assert brownout.BrownoutShed(3, 1.0, "hard").status == 429


def test_floor_signal_reads_zero_on_an_undrifted_link():
    """Review finding: the floor signal is normalized over the DRIFT —
    recent == lifetime min reads 0.0 pressure (no structural baseline
    that could trap a low --brownout-exit in a permanent shed state),
    and recent == floor_drift x min reads exactly 1.0."""
    class _Floor:
        def __init__(self, d):
            self._d = d

        def to_dict(self):
            return self._d

    class _Eng:
        def __init__(self, d):
            self.rpc_floor = _Floor(d)

        def _resident_flights(self):
            return []

    cfg = brownout.BrownoutConfig(floor_drift=4.0)
    sig = brownout.engine_signals(
        _Eng({"type": "min_est", "min": 50.0, "recent": 50.0}), cfg
    )["floor"]
    assert sig() == 0.0
    sig = brownout.engine_signals(
        _Eng({"type": "min_est", "min": 50.0, "recent": 200.0}), cfg
    )["floor"]
    assert sig() == pytest.approx(1.0)
    with pytest.raises(ValueError):
        brownout.BrownoutConfig(floor_drift=1.0)


def test_broken_or_empty_signals_read_as_silence():
    clock = FakeClock()

    def explode():
        raise RuntimeError("signal backend gone")

    ctrl = brownout.BrownoutController(
        brownout.BrownoutConfig(eval_interval_s=0.0),
        clock=clock,
        signals={"burn": explode, "queue": lambda: None},
    )
    assert ctrl.evaluate() == 0  # no usable signal = pressure 0, not a crash
    assert ctrl.metrics()["pressure"] == {}


def test_shed_observations_count_error_rate_total_but_skip_latency():
    """The shed-observation contract both ways (review finding): a shed
    response feeds the error-rate objective's TOTAL (as a non-error —
    refusals dilute the error fraction honestly) but is excluded from
    latency objectives entirely, so a storm of ~1 ms refusals cannot
    collapse the latency burn signal and flap the ladder that produced
    them."""
    clock = FakeClock()
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.1,solve_p95_ms<=250"),
        window_s=60.0, clock=clock, min_samples=1,
    )
    for _ in range(5):
        mon.observe(300.0 / 1e3, error=False, stream="solve")  # slow serves
    for _ in range(50):
        mon.observe(0.001, error=False, stream="solve", shed=True)
    snap = mon.burn_snapshot()
    lat = snap["solve_p95_ms<=250"]
    # 5 served observations, all over threshold — the 50 refusals did not
    # dilute the window.
    assert lat["window_total"] == 5 and lat["window_bad"] == 5
    assert lat["burning"]
    err = snap["error_rate<=0.1"]
    assert err["window_total"] == 55 and err["window_bad"] == 0
    # And a shed can never be an error, whatever the caller passed.
    mon.observe(0.001, error=True, stream="solve", shed=True)
    assert mon.burn_snapshot()["error_rate<=0.1"]["window_bad"] == 0


def test_burn_snapshot_read_api_and_decay():
    clock = FakeClock()
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.1,solve_p95_ms<=250"),
        window_s=12.0, clock=clock,
    )
    for _ in range(20):
        mon.observe(0.01, error=True, stream="solve")
    snap = mon.burn_snapshot()
    err = snap["error_rate<=0.1"]
    assert err["burn_rate"] == pytest.approx(10.0)
    assert err["headroom"] == pytest.approx(1.0 - 10.0)
    assert err["burning"] and err["window_total"] == 20
    assert err["window_bad"] == 20
    lat = snap["solve_p95_ms<=250"]
    assert lat["burn_rate"] == 0.0 and not lat["burning"]
    # The snapshot decays without traffic: the window ages out on reads.
    clock.advance(15.0)
    snap2 = mon.burn_snapshot()
    assert snap2["error_rate<=0.1"]["burn_rate"] == 0.0
    assert snap2["error_rate<=0.1"]["window_total"] == 0


# -- engine lane: shedding through the real front door -------------------------


def _engine(**kw):
    return SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=8,
        frontdoor=FrontDoorConfig(), **kw,
    ).start()


def test_stage2_sheds_easy_stage3_sheds_hard_cache_always_serves():
    clock, press = FakeClock(), [0.0]
    ctrl = _ctrl(clock, press, hold_s=0.0, quiet_s=1.0)
    eng = _engine()
    try:
        with brownout.installed(ctrl):
            # Healthy: both tiers serve (and the hard verdict fills the
            # canonical cache for the stage-3 assertion below).
            j_easy = eng.submit(_easy_open(0), saturation="reject")
            assert j_easy.wait(120) and j_easy.solved, j_easy.error
            j_hard = eng.submit(np.asarray(HARD_9[1]), saturation="reject")
            assert j_hard.wait(300) and j_hard.solved, j_hard.error
            # Force stage 2, then hold it inside the hysteresis band.
            press[0] = 2.0
            ctrl.evaluate()
            ctrl.evaluate()
            press[0] = 0.75
            assert ctrl.stage() == 2
            with pytest.raises(brownout.BrownoutShed) as exc:
                eng.submit(_easy_open(1), saturation="reject")
            assert exc.value.status == 503 and exc.value.shed_tier == "easy"
            assert exc.value.retry_after_s > 0
            # The hard tail still serves at stage 2.
            j2 = eng.submit(np.asarray(HARD_9[2]), saturation="reject")
            assert j2.wait(300) and j2.solved, j2.error
            # Stage 3: anything costing a dispatch is refused with 429...
            press[0] = 2.0
            ctrl.evaluate()
            press[0] = 0.75
            assert ctrl.stage() == 3
            with pytest.raises(brownout.BrownoutShed) as exc3:
                eng.submit(np.asarray(HARD_9[0]), saturation="reject")
            assert exc3.value.status == 429 and exc3.value.shed_tier == "hard"
            # ...but a cache hit costs nothing and serves even at stage 3.
            jc = eng.submit(np.asarray(HARD_9[1]), saturation="reject")
            assert jc.wait(60) and jc.solved and jc.route == "cache"
            m = ctrl.metrics()
            assert m["shed"] == {"easy": 1, "hard": 1}
            assert m["shed_by_stage"][2] == 1 and m["shed_by_stage"][3] == 1
    finally:
        eng.stop(timeout=2)


def test_quiet_fallback_submits_degrade_instead_of_shedding():
    """Internal callers (cluster re-execution, library users) never see a
    BrownoutShed: at shed stages their easy boards run native-only and
    their hard boards still reach the device."""
    clock, press = FakeClock(), [2.0]
    ctrl = _ctrl(clock, press, hold_s=0.0)
    eng = _engine()
    try:
        with brownout.installed(ctrl):
            for _ in range(3):
                ctrl.evaluate()
            press[0] = 0.75
            assert ctrl.stage() == 3
            j_easy = eng.submit(_easy_open(2))  # default saturation=fallback
            assert j_easy.wait(120) and j_easy.done.is_set()
            assert j_easy.route in ("native", "propagation")
            j_hard = eng.submit(np.asarray(HARD_9[0]))
            assert j_hard.wait(300) and j_hard.solved, j_hard.error
            assert ctrl.metrics()["shed_total"] == 0
    finally:
        eng.stop(timeout=2)


def test_stage1_native_only_suppresses_device_shadow(monkeypatch):
    """Stage 1 reclaims the easy tier's device lanes: the race's shadow
    fallback is provably never submitted, while stage 0 still submits it
    once the native head start elapses."""
    from distributed_sudoku_solver_tpu import native

    if not native.available():  # pragma: no cover - no compiler
        pytest.skip("native DFS unavailable")
    clock, press = FakeClock(), [0.0]
    ctrl = _ctrl(clock, press, hold_s=0.0, quiet_s=1.0)
    eng = SolverEngine(
        config=SMALL, max_batch=8, chunk_steps=8,
        frontdoor=FrontDoorConfig(native_head_start_s=0.05),
    ).start()
    shadows = []
    real_submit = eng.submit

    def counting_submit(*a, **kw):
        if kw.get("shadow"):
            shadows.append(kw)
        return real_submit(*a, **kw)

    monkeypatch.setattr(eng, "submit", counting_submit)
    # Hold the native entrant so the head start always elapses first —
    # deterministic either way, instead of racing a fast native win.
    release = threading.Event()
    real_solve = native.solve

    def slow_solve(grid, geom=None):
        release.wait(5.0)
        return real_solve(grid, geom) if geom is not None else real_solve(grid)

    monkeypatch.setattr(native, "solve", slow_solve)
    try:
        with brownout.installed(ctrl):
            press[0] = 2.0
            ctrl.evaluate()
            press[0] = 0.75
            assert ctrl.stage() == 1
            job = eng.submit(_easy_open(3), saturation="reject")
            assert job.route == "native"  # admitted, racing native-only
            release.set()
            assert job.wait(120) and job.solved, job.error
            assert job.route == "native"
            assert shadows == [], "stage 1 submitted a device shadow"
            # Stage 0 twin: same race, fallback allowed — the shadow IS
            # submitted after the head start.
            release.clear()
            press[0] = 0.0
            for _ in range(3):
                clock.advance(2.0)
                ctrl.evaluate()
            assert ctrl.stage() == 0
            job0 = eng.submit(_easy_open(4), saturation="reject")
            deadline = threading.Event()
            for _ in range(100):
                if shadows:
                    break
                deadline.wait(0.05)
            release.set()
            assert job0.wait(120) and job0.done.is_set()
            assert shadows, "stage 0 never submitted the device fallback"
    finally:
        release.set()
        eng.stop(timeout=2)


def test_native_only_backstop_resolves_a_decline(monkeypatch):
    """With the fallback suppressed, a native decline must still resolve
    the job (an error, not a hang)."""
    from distributed_sudoku_solver_tpu import native
    from distributed_sudoku_solver_tpu.serving.engine import Job
    from distributed_sudoku_solver_tpu.serving.portfolio import race_native

    monkeypatch.setattr(native, "available", lambda: False)
    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9 as G

    eng = SolverEngine(config=SMALL, max_batch=8, chunk_steps=8).start()
    try:
        job = Job(uuid="bo-backstop", grid=_easy_open(0), geom=G)
        job.submitted_at = eng._clock()
        race_native(eng, job, head_start_s=0.01, device_fallback=False)
        assert job.wait(10), "backstop never resolved the declined race"
        assert not job.solved and job.error is not None
        assert "declined" in job.error
    finally:
        eng.stop(timeout=2)


def test_e2e_504_storm_walks_ladder_up_and_back_down():
    """The overload acceptance (ISSUE 15): a seeded 504-storm burns the
    solve stream, the ladder walks 1 -> 2 -> 3, only easy-tier jobs shed
    while ZERO hard-tier jobs are lost (every hard submit either solves
    or gets an honest BrownoutShed), and recovery walks back to 0 with
    every transition counted exactly once."""
    clock = FakeClock()
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.05"), window_s=10.0, clock=clock
    )
    ctrl = brownout.BrownoutController(
        brownout.BrownoutConfig(
            enter=1.0, exit=0.5, quiet_s=2.0, hold_s=0.5, eval_interval_s=0.0
        ),
        clock=clock,
    )
    eng = _engine()
    ctrl.set_signals(brownout.engine_signals(eng, ctrl.config))
    hard_outcomes = []
    try:
        with slo.installed(mon), brownout.installed(ctrl):
            # Baseline: healthy traffic, stage 0, hard board solves (and
            # fills the cache for the recovery phase).
            j = eng.submit(np.asarray(HARD_9[1]), saturation="reject")
            assert j.wait(300) and j.solved, j.error
            hard_outcomes.append("solved")
            # The storm: clients time out (HTTP 504s recorded as errors
            # on the solve stream, exactly what serving/http.py does).
            for _ in range(30):
                mon.observe(0.3, error=True, stream="solve")
            stages_seen = []
            for _ in range(6):
                stages_seen.append(ctrl.evaluate())
                clock.advance(0.6)
            assert stages_seen[-1] == 3 and ctrl.stage_entered[1:] == [1, 1, 1]
            # Stage 3: easy AND hard shed honestly — never silently lost.
            with pytest.raises(brownout.BrownoutShed) as e_easy:
                eng.submit(_easy_open(5), saturation="reject")
            assert e_easy.value.shed_tier == "easy"
            with pytest.raises(brownout.BrownoutShed) as e_hard:
                eng.submit(np.asarray(HARD_9[0]), saturation="reject")
            hard_outcomes.append(f"shed:{e_hard.value.status}")
            assert e_hard.value.status == 429
            # Recovery: the window ages the errors out; quiet windows walk
            # the ladder down one stage at a time.
            clock.advance(12.0)
            down = []
            for _ in range(6):
                clock.advance(2.1)
                down.append(ctrl.evaluate())
            assert down[-1] == 0 and ctrl.stage() == 0
            assert ctrl.transitions == 6
            assert ctrl.escalations == 3 and ctrl.deescalations == 3
            # Back to serving: the hard tier answers again (cache hit —
            # zero hard-tier verdicts were lost across the excursion).
            j2 = eng.submit(np.asarray(HARD_9[1]), saturation="reject")
            assert j2.wait(60) and j2.solved
            hard_outcomes.append("solved")
            assert all(
                o == "solved" or o.startswith("shed:") for o in hard_outcomes
            )
            m = ctrl.metrics()
            assert m["shed"] == {"easy": 1, "hard": 1}
    finally:
        eng.stop(timeout=2)


def test_disabled_path_microcheck(monkeypatch):
    """No controller installed: the serving path must never touch the
    controller surface — gate/evaluate monkeypatched to explode, a solve
    still runs (the disabled path is one global read + one branch)."""
    def explode(*a, **kw):  # pragma: no cover - must never run
        raise AssertionError("brownout surface touched with no controller")

    monkeypatch.setattr(brownout.BrownoutController, "gate", explode)
    monkeypatch.setattr(brownout.BrownoutController, "evaluate", explode)
    monkeypatch.setattr(brownout.BrownoutController, "stage", explode)
    assert brownout.active() is None
    eng = _engine()
    try:
        j = eng.submit(_easy_open(6), saturation="reject")
        assert j.wait(120) and j.done.is_set()
        assert "brownout" not in eng.metrics()
    finally:
        eng.stop(timeout=2)


# -- http lane: the shed-response contract -------------------------------------


def test_http_shed_body_retry_after_and_slo_non_error():
    """Satellite pin (ISSUE 15): every shed response carries the
    machine-readable body {stage, retry_after_s, shed_tier} + Retry-After,
    and is recorded into the `solve` SLO stream as a NON-error — shedding
    must not burn the error-rate objective it exists to protect."""
    from distributed_sudoku_solver_tpu.serving.http import (
        ApiServer,
        StandaloneNode,
    )

    clock, press = FakeClock(), [0.0]
    ctrl = _ctrl(clock, press, hold_s=0.0, retry_after_s=7.0)
    mon = slo.SloMonitor(
        slo.parse_slo("error_rate<=0.5,solve_p95_ms<=250"),
        window_s=60.0, min_samples=1,
    )
    eng = _engine()
    api = ApiServer(StandaloneNode(eng), host="127.0.0.1", port=0).start()
    try:
        with slo.installed(mon), brownout.installed(ctrl):
            press[0] = 2.0
            ctrl.evaluate()
            ctrl.evaluate()
            press[0] = 0.75
            assert ctrl.stage() == 2
            body = json.dumps(
                {"sudoku": _easy_open(1).tolist()}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{api.port}/solve", data=body,
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=30)
            e = err.value
            assert e.code == 503
            assert e.headers["Retry-After"] == "7"
            shed_body = json.loads(e.read())
            assert shed_body["stage"] == 2
            assert shed_body["shed_tier"] == "easy"
            assert shed_body["retry_after_s"] == pytest.approx(7.0)
            # The pin: observed on the solve stream, NOT as an error —
            # and excluded from the latency objective's window entirely.
            objectives = mon.metrics()["objectives"]
            state = objectives["error_rate<=0.5"]
            assert state["window_total"] >= 1
            assert state["window_bad"] == 0, (
                "a 503 shed burned the error-rate objective it protects"
            )
            assert objectives["solve_p95_ms<=250"]["window_total"] == 0, (
                "a shed response diluted the latency objective's window"
            )
            # /slo surfaces the burn snapshot the controller acts on.
            raw = urllib.request.urlopen(
                f"http://127.0.0.1:{api.port}/slo", timeout=30
            ).read()
            doc = json.loads(raw)
            assert "burn" in doc
            assert doc["burn"]["error_rate<=0.5"]["burn_rate"] == 0.0
    finally:
        api.stop()
        eng.stop(timeout=2)


# -- rollup / status lane ------------------------------------------------------


def test_agg_rollup_merges_brownout_and_status_turns_amber():
    from distributed_sudoku_solver_tpu.obs import agg

    def body(stage, shed_easy, transitions):
        return {
            "brownout": {
                "stage": stage,
                "transitions": transitions,
                "escalations": transitions,
                "deescalations": 0,
                "shed_total": shed_easy,
                "shed": {"easy": shed_easy, "hard": 0},
                "stage_residency_s": [10.0, 2.0, 1.0, 0.0],
            }
        }

    ru = agg.rollup([body(0, 0, 0), body(2, 5, 2), body(1, 3, 1)])
    bo = ru["brownout"]
    assert bo["stage_max"] == 2 and bo["browning_members"] == 2
    assert bo["transitions"] == 3 and bo["shed_total"] == 8
    assert bo["shed"] == {"easy": 8, "hard": 0}
    assert bo["stage_residency_s"] == [30.0, 6.0, 3.0, 0.0]

    view = {
        "address": "a:1", "coordinator": "a:1", "view": [0, 1],
        "nodes": {
            "a:1": {"unreachable": False, "stale": False,
                    "metrics": body(0, 0, 0)},
            "b:2": {"unreachable": False, "stale": False,
                    "metrics": body(2, 5, 2)},
        },
        "rollup": ru,
    }
    status = agg.status_from(view)
    assert status["brownout_members"] == ["b:2"]
    assert status["state"] == "amber"
    assert status["healthy"]  # amber is shedding-by-choice, not an outage
    # No brownout anywhere: green.
    view["nodes"]["b:2"]["metrics"] = body(0, 0, 0)
    assert agg.status_from(view)["state"] == "green"
    # A burning member outranks amber: red.
    view["nodes"]["b:2"]["metrics"] = {
        **body(3, 9, 3), "slo": {"burning": True},
    }
    st = agg.status_from(view)
    assert st["state"] == "red" and st["brownout_members"] == ["b:2"]
