"""Cluster control-plane tests: join, dispatch, failure, recovery (SURVEY.md §4
items 3-4, the §3.4 kill-scenario automated).

These exercise membership/heartbeat/re-execution logic only, so the engines
run an oracle-backed solve_fn — no device in the loop, sub-second tests.

Since round 10 this file is the REAL-SOCKET smoke lane: it keeps the
production transport (cluster/wire.py TcpTransport) covered end to end,
while the timing-fragile scenarios (false death, part re-homing,
coordinator promotion, split-brain, duplicate delivery) live in
tests/test_simnet.py on the deterministic in-memory plane with a virtual
clock.  The two slowest wall-clock-bound recovery scenarios here are
marked ``slow`` — their deterministic twins run in tier-1 instead.
"""

import dataclasses
import time
from types import SimpleNamespace

import numpy as np
import pytest

from distributed_sudoku_solver_tpu.cluster.node import ClusterConfig, ClusterNode
from distributed_sudoku_solver_tpu.serving.engine import SolverEngine
from distributed_sudoku_solver_tpu.utils.oracle import is_valid_solution, solve_oracle
from distributed_sudoku_solver_tpu.utils.puzzles import EASY_9

# Detection threshold = heartbeat_s * fail_factor = 4 s: fast enough for the
# kill-tests below (their wait_for budgets are >= 10 s), high enough not to
# false-positive when the suite's XLA compiles peg every core and starve the
# heartbeat threads — a false death during ring formation is unrecoverable
# for the fixture, so this errs well on the side of patience.
FAST = ClusterConfig(heartbeat_s=0.25, fail_factor=16.0, io_timeout_s=2.0)


def oracle_solve_fn(delay: float = 0.0):
    def fn(grids, geom, cfg):
        g = np.asarray(grids)
        sols, solved = [], []
        for i in range(g.shape[0]):
            if delay:
                time.sleep(delay)
            s = solve_oracle(g[i], geom)
            solved.append(s is not None)
            sols.append(s if s is not None else np.zeros_like(g[i]))
        solved = np.asarray(solved)
        return SimpleNamespace(
            solved=solved,
            unsat=~solved,
            solution=np.stack(sols),
            nodes=np.full(g.shape[0], 7),
        )

    return fn


def make_node(anchor=None, delay=0.0):
    import os

    if os.environ.get("DSST_SOAK_DEVICE") == "1":
        # Device-backed soak lane (VERDICT r3 #6): the engines run the real
        # chunked flight loop against JAX devices (the forced-CPU mesh in
        # this harness; the same code path a TPU deployment runs), so jit
        # caches, device buffers, and transfer pools — the things that
        # actually grow in a JAX process — are inside the leak-curve
        # measurement, not stubbed out by the oracle.
        from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

        engine = SolverEngine(
            config=SolverConfig(min_lanes=8, stack_slots=16),
            max_batch=8,
            handicap_s=delay,
        ).start()
    else:
        engine = SolverEngine(
            solve_fn=oracle_solve_fn(delay), batch_window_s=0.001
        ).start()
    return ClusterNode(engine, anchor=anchor, config=FAST).start()


def wait_for(pred, timeout=15.0, every=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return False


@pytest.fixture
def trio():
    a = make_node()
    b = make_node(anchor=a.addr)
    c = make_node(anchor=a.addr)
    nodes = [a, b, c]
    assert wait_for(lambda: all(len(n.network) == 3 for n in nodes), timeout=30)
    yield nodes
    for n in nodes:
        n.kill()
        n.engine.stop(timeout=1)


def test_ring_formation(trio):
    a, b, c = trio
    assert all(n.coordinator == a.addr_s for n in trio)
    view = a.network_view()
    assert set(view) == {a.addr_s, b.addr_s, c.addr_s}
    # Every node's [pred, succ] chain is a single consistent ring.
    succ_map = {m: ps[1] for m, ps in view.items()}
    seen, cur = [], a.addr_s
    for _ in range(3):
        seen.append(cur)
        cur = succ_map[cur]
    assert cur == a.addr_s and len(set(seen)) == 3


def test_remote_dispatch_and_solution(trio):
    a, b, c = trio
    jobs = [a.submit(EASY_9) for _ in range(6)]
    for j in jobs:
        assert j.wait(10)
        assert j.solved
        assert is_valid_solution(j.solution)
    # Least-outstanding dispatch spread work beyond the local engine.
    remote_done = b.engine.stats()["jobs_done"] + c.engine.stats()["jobs_done"]
    assert remote_done > 0


def test_graceful_leave_updates_all(trio):
    a, b, c = trio
    c.stop(graceful=True)
    assert wait_for(
        lambda: len(a.network) == 2 and len(b.network) == 2 and c.addr_s not in a.network
    )


def test_dead_node_detected_and_ring_repaired(trio):
    a, b, c = trio
    c.kill()
    assert wait_for(lambda: all(len(n.network) == 2 for n in (a, b)))
    assert c.addr_s not in a.network
    view = a.network_view()
    assert view[a.addr_s] == [b.addr_s, b.addr_s]


def test_coordinator_death_promotes_detector(trio):
    a, b, c = trio
    assert a.coordinator == a.addr_s
    a.kill()
    assert wait_for(
        lambda: all(
            len(n.network) == 2 and n.coordinator != a.addr_s for n in (b, c)
        ),
    )
    assert b.coordinator == c.coordinator
    assert b.coordinator in (b.addr_s, c.addr_s)


def test_reexecution_after_member_death(trio):
    a, b, c = trio
    # Slow down b and c so a forwarded job is still in flight when we kill.
    slow = oracle_solve_fn(delay=1.0)
    b.engine._solve_fn = slow
    c.engine._solve_fn = slow
    job = a._submit_remote(np.asarray(EASY_9, dtype=np.int32), b.addr_s)
    time.sleep(0.2)  # let the TASK land in b's queue
    b.kill()
    assert job.wait(15), "forwarded job must be re-executed after member death"
    assert job.solved
    assert is_valid_solution(job.solution)


def test_send_failure_falls_back_to_local():
    a = make_node()
    try:
        # Member address that is not listening: reliable transport notices and
        # the job re-executes locally instead of being lost (§2.5 #7).
        job = a._submit_remote(
            np.asarray(EASY_9, dtype=np.int32), "127.0.0.1:1"
        )
        assert job.wait(10)
        assert job.solved
    finally:
        a.kill()
        a.engine.stop(timeout=1)


def _flight_node(
    anchor=None,
    handicap: float = 0.0,
    cluster_cfg: ClusterConfig = FAST,
):
    """Node over a real (flight-loop) engine — the offload/progress paths
    need chunked device execution, which the oracle solve_fn bypasses."""
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig

    engine = SolverEngine(
        config=SolverConfig(min_lanes=4, stack_slots=32, branch="first"),
        chunk_steps=1,
        handicap_s=handicap,
        batch_window_s=0.001,
    ).start()
    return ClusterNode(engine, anchor=anchor, config=cluster_cfg).start()


def _warm(engine):
    """Compile the flight shapes once so chunk cadence dominates the test."""
    w = engine.submit(EASY_9)
    assert w.wait(120)


def _deep_unsat_board():
    """HARD_9[1] with one consistent-looking wrong clue: proving unsat takes
    ~129 frontier steps at 4 lanes — a search the cluster can share."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    g = np.asarray(HARD_9[1]).copy()
    g[1, 6] = 8
    return g


def test_midjob_offload_to_idle_peer():
    """VERDICT r1 #3: a loaded (handicapped) node sheds live subtree rows to
    an idle peer via NEEDWORK/SUBTASK, the peer's exhaustion composes into
    the unsat proof, and sharing beats solo wall-clock."""
    ccfg = ClusterConfig(
        heartbeat_s=0.2,
        fail_factor=64.0,
        io_timeout_s=2.0,
        needwork=True,
        shed_k=4,
        progress_interval_s=0.0,
    )
    board = _deep_unsat_board()
    # Solo baseline: same engine config + handicap, no peers.
    solo = _flight_node(cluster_cfg=dataclasses.replace(ccfg, needwork=False))
    a = b = None
    try:
        solo.engine.handicap_s = 0.0
        _warm(solo.engine)
        solo.engine.handicap_s = 0.05
        t0 = time.monotonic()
        sj = solo._submit_local(board)
        assert sj.wait(120)
        t_solo = time.monotonic() - t0
        assert sj.unsat

        a = _flight_node(handicap=0.0, cluster_cfg=ccfg)
        b = _flight_node(anchor=a.addr, handicap=0.0, cluster_cfg=ccfg)
        assert wait_for(lambda: len(a.network) == 2 and len(b.network) == 2, timeout=30)
        _warm(a.engine)
        _warm(b.engine)
        a.engine.handicap_s = 0.05  # a is the slow, loaded node
        t0 = time.monotonic()
        job = a._submit_local(board)
        assert job.wait(120)
        t_cluster = time.monotonic() - t0
        # Exhaustion aggregated across every shipped part: still a proof.
        assert job.unsat and not job.solved
        assert a.subtasks_sent >= 1, "busy node never shed work"
        assert b.subtasks_run >= 1, "idle peer never ran a subtask"
        assert t_cluster < t_solo, (
            f"sharing did not beat solo: {t_cluster:.2f}s vs {t_solo:.2f}s"
        )
    finally:
        for n in (solo, a, b):
            if n is not None:
                n.kill()
                n.engine.stop(timeout=1)


@pytest.mark.slow
def test_part_recovery_after_peer_death():
    """ADVICE r2 #1: a SUBTASK part whose executing peer dies is re-entered
    locally from the rows retained at shed time, so the root job still
    finalizes — including the exhaustion path, which requires every part's
    subspace to be accounted for."""
    ccfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=8.0,
        io_timeout_s=2.0,
        needwork=True,
        shed_k=4,
        progress_interval_s=0.0,
    )
    board = _deep_unsat_board()
    a = _flight_node(cluster_cfg=ccfg)
    b = _flight_node(anchor=a.addr, cluster_cfg=ccfg)
    try:
        assert wait_for(lambda: len(a.network) == 2 and len(b.network) == 2, timeout=30)
        _warm(a.engine)
        _warm(b.engine)
        # a is slow enough that the search outlives b's death + detection
        # (~2 s); b is so slow its stolen part cannot finish before then, so
        # the part is genuinely lost and must be recovered from the retained
        # rows, not completed by b's lingering engine thread.
        a.engine.handicap_s = 0.05
        b.engine.handicap_s = 1.0
        job = a._submit_local(board)
        assert wait_for(
            lambda: a.subtasks_sent >= 1 and b.subtasks_run >= 1, timeout=60
        ), "idle peer never stole a part"
        assert not job.done.is_set()
        b.kill()
        assert job.wait(120), "job never finalized after part-executing peer died"
        # The recovered part ran here (subtasks_run counts local re-entry)
        # and its exhaustion composed into a complete unsat proof.
        assert a.subtasks_run >= 1, "lost part was not re-entered locally"
        assert job.unsat and not job.solved
    finally:
        for n in (a, b):
            n.kill()
            n.engine.stop(timeout=1)


@pytest.mark.slow
def test_resume_from_progress_snapshot():
    """VERDICT r1 #4: a worker streams PROGRESS snapshots; when it dies, the
    origin resumes mid-subtree and provably skips already-searched work
    (nodes accounting), instead of restarting from the clue grid."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    ccfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=8.0,
        io_timeout_s=2.0,
        needwork=False,
        progress_interval_s=0.1,
    )
    board = np.asarray(HARD_9[1])  # 46 steps at 4 lanes: a long search
    o = _flight_node(cluster_cfg=ccfg)
    w = _flight_node(anchor=o.addr, handicap=0.0, cluster_cfg=ccfg)
    try:
        assert wait_for(lambda: len(o.network) == 2 and len(w.network) == 2, timeout=30)
        _warm(o.engine)
        _warm(w.engine)
        # Full-search cost from scratch, for the skipped-work comparison.
        ref = o.engine.submit(board)
        assert ref.wait(120) and ref.solved
        nodes_full = ref.nodes
        assert nodes_full > 0

        w.engine.handicap_s = 0.1  # slow the worker so we can kill mid-solve
        job = o._submit_remote(board.astype(np.int32), w.addr_s)
        assert wait_for(
            lambda: o._ledger.get(job.uuid, {}).get("nodes_done", 0) >= 5
            and not job.done.is_set(),
            timeout=60,
        ), "no usable PROGRESS snapshot arrived"
        base = o._ledger[job.uuid]["nodes_done"]
        w.kill()
        assert job.wait(120), "job must be re-executed after worker death"
        assert job.solved
        assert is_valid_solution(job.solution)
        # The resume carried the dead worker's progress: total nodes include
        # the snapshot baseline, and the locally re-searched remainder is
        # strictly smaller than a from-scratch search.
        assert job.nodes >= base
        assert job.nodes - base < nodes_full, (
            f"resume did not skip searched work: local {job.nodes - base} "
            f"vs full {nodes_full}"
        )
    finally:
        for n in (o, w):
            n.kill()
            n.engine.stop(timeout=1)


def test_metrics_view_counters(trio):
    a, b, c = trio
    jobs = [a.submit(EASY_9) for _ in range(3)]
    for j in jobs:
        assert j.wait(10)
    m = a.metrics_view()
    cl = m["cluster"]
    assert cl["address"] == a.addr_s
    assert cl["coordinator"] == a.addr_s
    assert cl["members"] == 3
    assert cl["view"][0] == 0 and cl["view"][1] >= 2  # two joins bumped epoch
    assert cl["ledger_outstanding"] == 0  # everything resolved
    # Counter semantics are pinned by test_midjob_offload_to_idle_peer
    # (asserts positive counts after a real shed); here just key presence.
    assert {"subtasks_sent", "subtasks_run", "parts_running"} <= set(cl)
    assert "jobs_done" in m  # engine metrics merged in


def test_stats_aggregation(trio):
    a, b, c = trio
    jobs = [a.submit(EASY_9) for _ in range(4)]
    for j in jobs:
        assert j.wait(10)
    stats = a.stats_view()
    assert stats["all"]["solved"] == 4
    assert len(stats["nodes"]) == 3
    total = sum(n["validations"] or 0 for n in stats["nodes"])
    assert stats["all"]["validations"] == total == 4 * 7


def test_engine_stopped_solution_reexecutes_not_finalizes():
    """Round-4 soak finding: a member whose engine is stopping drains its
    jobs with error='engine stopped' and pushes that NON-verdict back as a
    SOLUTION — which used to beat failure detection to the origin's
    ledger and finalize the client's job unsolved.  The origin must treat
    it as a failed execution and re-execute from the ledger instead."""
    a = make_node()
    try:
        g = np.asarray(EASY_9, np.int32)
        # Manufacture the ledger state _submit_remote leaves behind for a
        # job shipped to a (here: fictitious) member.
        from distributed_sudoku_solver_tpu.cluster.node import Job as CJob

        ju = f"{a.addr_s}/test-engine-stopped"
        handle = CJob(uuid=ju, grid=g, geom=a_geom(g))
        with a._lock:
            a._ledger[ju] = {
                "grid": g, "member": "127.0.0.1:1", "job": handle,
                "config": None,
            }
        a._track("127.0.0.1:1", +1)
        a._on_solution(
            {
                "method": "SOLUTION", "uuid": ju, "solved": False,
                "unsat": False, "cancelled": False, "nodes": 0,
                "error": "engine stopped", "solution": None,
            }
        )
        assert handle.done.wait(30), "job neither re-executed nor finalized"
        assert handle.solved, (
            f"engine-stopped drain finalized the job unsolved "
            f"(error={handle.error!r})"
        )
        assert is_valid_solution(handle.solution)
        with a._lock:
            assert ju not in a._ledger  # re-execution consumed the entry
    finally:
        a.kill()
        a.engine.stop(timeout=1)


def test_permanent_remote_error_finalizes_without_reexecution():
    """Round-9 twin of the test above, from the other side of the fault
    taxonomy (serving/faults.py): a SOLUTION carrying a PERMANENT error —
    one a retry cannot cure — must finalize the client's job with that
    error instead of burning a local re-execution that would fail
    identically.  Transient errors (previous test) still re-execute."""
    a = make_node()
    try:
        g = np.asarray(EASY_9, np.int32)
        from distributed_sudoku_solver_tpu.cluster.node import Job as CJob

        ju = f"{a.addr_s}/test-permanent-error"
        handle = CJob(uuid=ju, grid=g, geom=a_geom(g))
        with a._lock:
            a._ledger[ju] = {
                "grid": g, "member": "127.0.0.1:1", "job": handle,
                "config": None,
            }
        a._track("127.0.0.1:1", +1)
        a._on_solution(
            {
                "method": "SOLUTION", "uuid": ju, "solved": False,
                "unsat": False, "cancelled": False, "nodes": 0,
                "error": "ValueError: lanes must divide the mesh",
                "solution": None,
            }
        )
        assert handle.done.wait(30), "permanent error never finalized"
        assert not handle.solved
        assert handle.error and "ValueError" in handle.error
        with a._lock:
            assert ju not in a._ledger  # finalized, not re-queued
    finally:
        a.kill()
        a.engine.stop(timeout=1)


def a_geom(g):
    from distributed_sudoku_solver_tpu.models.geometry import geometry_for_size

    return geometry_for_size(g.shape[0])


def test_errored_part_result_never_counts_as_verdict():
    """The PART_RESULT twin: a part drained by a stopping peer engine (or
    failed by any no-verdict error) must never be marked done — it
    re-enters locally, and if that re-entry itself fails, the part stays
    pending with its recovery rows retained for deadline/view recovery."""
    from distributed_sudoku_solver_tpu.cluster.node import _Exec, pack_rows
    from distributed_sudoku_solver_tpu.serving.engine import Job as EngineJob

    a = make_node()
    try:
        g = np.asarray(EASY_9, np.int32)
        # An unresolved local job handle: the aggregate must stay live so
        # the part bookkeeping (not finalization) is what's under test.
        eng_job = EngineJob(uuid="x-part-test", grid=g, geom=a_geom(g))
        ex = _Exec(a, eng_job, on_final=lambda r: None)
        rows = pack_rows(np.ones((2, 9, 9), np.uint32))
        assert ex.add_part("p1", "127.0.0.1:2", rows_packed=rows, config=None)
        # Make the immediate local re-entry fail deterministically: with
        # the engine stopped, _on_subtask's submit_roots raises — the
        # fallback branch (stay pending, rows retained, flag cleared for a
        # later recovery pass) is what's pinned here.
        a.engine.stop(timeout=2)
        ex.on_part_result(
            "p1",
            {"solved": False, "unsat": False, "nodes": 3,
             "error": "engine stopped", "solution": None},
        )
        with ex.lock:
            p = ex.parts["p1"]
            assert not p["done"], "errored part wrongly counted as verdict"
            assert p["rows"] is not None, "recovery rows freed prematurely"
            assert not p["rehomed"], "failed re-entry must clear the flag"
        # A real exhaustion verdict still lands normally afterwards.
        ex.on_part_result(
            "p1",
            {"solved": False, "unsat": True, "nodes": 3,
             "error": None, "solution": None},
        )
        with ex.lock:
            assert ex.parts["p1"]["done"]
            assert ex.parts["p1"]["exhausted"]
    finally:
        a.kill()
        a.engine.stop(timeout=1)


def test_errored_local_part_result_is_terminal_not_a_loop():
    """A no-verdict error from the part's LOCAL re-entry (the last resort)
    must terminate: the part goes failed-done and an unresolved job
    surfaces the cause as its error — re-entering again would fail
    identically forever (an unbounded resubmit loop, caught in review)."""
    from distributed_sudoku_solver_tpu.cluster.node import _Exec
    from distributed_sudoku_solver_tpu.serving.engine import Job as EngineJob

    a = make_node()
    try:
        g = np.asarray(EASY_9, np.int32)
        finals: list = []
        eng_job = EngineJob(uuid="x-term-test", grid=g, geom=a_geom(g))
        # The local search already exhausted its (shed-incomplete) space.
        eng_job.exhausted = True
        ex = _Exec(a, eng_job, on_final=finals.append)
        assert ex.add_part("p1", "127.0.0.1:2", rows_packed={"d": 1}, config=None)
        with ex.lock:
            ex.parts["p1"]["rehomed"] = True  # a re-entry had been attempted
        ex.on_part_result(
            "p1",
            {"solved": False, "unsat": False, "nodes": 0, "local": True,
             "error": "ValueError: deterministic config failure",
             "solution": None},
        )
        with ex.lock:
            assert ex.parts["p1"]["done"], "terminal local failure must close the part"
            assert not ex.parts["p1"]["exhausted"]
        # The job resolves: error carries the cause, and no unsat claim is
        # made over the lost subtree.
        eng_job.done.set()
        ex._maybe_finalize()
        assert finals, "aggregate never finalized after terminal part loss"
        assert finals[0]["error"] and "last-resort" in finals[0]["error"]
        assert not finals[0]["unsat"] and not finals[0]["solved"]
    finally:
        a.kill()
        a.engine.stop(timeout=1)


def test_progress_skip_is_visible_not_silent():
    """Round 6 (VERDICT r5 missing #3): a frontier wider than
    progress_max_rows must not lose mid-subtree resume SILENTLY — the
    worker counts every skipped snapshot, warns, and exports the counter on
    metrics_view (/metrics), while the origin's ledger visibly never
    receives rows (resume degrades to root re-execution)."""
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9

    ccfg = ClusterConfig(
        heartbeat_s=0.25,
        fail_factor=16.0,
        io_timeout_s=2.0,
        needwork=False,
        progress_interval_s=0.05,
        progress_max_rows=0,  # every snapshot exceeds the cap
    )
    board = np.asarray(HARD_9[1])  # long search: many snapshot attempts
    o = _flight_node(cluster_cfg=ccfg)
    w = _flight_node(anchor=o.addr, cluster_cfg=ccfg)
    try:
        assert wait_for(
            lambda: len(o.network) == 2 and len(w.network) == 2, timeout=30
        )
        _warm(o.engine)
        _warm(w.engine)
        w.engine.handicap_s = 0.05  # slow chunks: snapshots happen mid-solve
        job = o._submit_remote(board.astype(np.int32), w.addr_s)
        assert wait_for(lambda: w.progress_skipped > 0, timeout=60), (
            "skipped snapshots were not counted"
        )
        # Degraded resume is now *reported*, and the ledger honestly holds
        # no mid-subtree rows for the job.
        assert "rows" not in o._ledger.get(job.uuid, {})
        assert w.metrics_view()["cluster"]["progress_skipped"] > 0
        w.engine.handicap_s = 0.0
        assert job.wait(120)
        assert job.solved
        assert is_valid_solution(job.solution)
    finally:
        for n in (o, w):
            n.kill()
            n.engine.stop(timeout=1)
