"""Headline benchmark: hard-9x9 bulk throughput (boards solved/s) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Protocol: the full bulk pipeline (``ops/bulk.py``: one-dispatch frontier
chunks — propagation, search, gang-up and cancellation all in-graph) over a
corpus of 65,536 FULLY DISTINCT boards — 65,533 generated 24-clue puzzles
(harder than typical 17-clue sets: ~45% resist propagation alone) plus the
three famous hard benchmark boards (rounds 1-3 tiled a 2,048-board corpus
16-32x; round 4 retired the tiling asterisk — measured deltas vs the tiled
corpus are in BENCHMARKS.md).  Generation is cached on disk
(``benchmarks/pregen_corpus.py`` pre-fills it in ~4 min parallel; a cold
cache regenerates inline, ~35 min single-threaded).  The timed run is the
*second* full pass (steady-state; compiles and host caches warm).

Timing forces a host-side value fetch per pass (``np.asarray``) —
``block_until_ready`` does not reliably block through the axon RPC tunnel
(measured: returns in <1 ms while the device still runs), so only a real
value round-trip is trustworthy.

Baseline: the reference solves one easy 9x9 via `POST /solve` in 3.13 s on
this container (BASELINE.md, measured from /root/reference/DHT_Node.py live)
— an effective 0.3195 boards/s/node.  ``vs_baseline`` is our boards/s over
that figure: a direct end-to-end speedup multiple on a *harder* workload.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_BOARDS_PER_S = 1.0 / 3.13  # reference: easy 9x9 end-to-end (BASELINE.md)
REPO = os.path.dirname(os.path.abspath(__file__))


def main() -> None:
    os.environ.setdefault("DSST_PUZZLE_CACHE", os.path.join(REPO, ".cache", "puzzles"))

    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".cache", "xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.bulk import BulkConfig, solve_bulk
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    distinct = puzzle_batch(SUDOKU_9, 65536 - len(HARD_9), seed=7, n_clues=24)
    grids = np.concatenate([np.stack(HARD_9), distinct]).astype(np.int32)
    b = grids.shape[0]

    cfg = BulkConfig()  # extended rules, 65,536-lane one-dispatch chunks
    solve_bulk(grids, SUDOKU_9, cfg)  # cold pass: compiles every rung shape
    # Best of 3 timed passes: host/tunnel load jitters single-pass wall
    # clock by 2x run to run; min-wall is the standard robust protocol.
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        res = solve_bulk(grids, SUDOKU_9, cfg)
        dt = min(dt, time.perf_counter() - t0)

    solved = int(res.solved.sum())
    boards_per_s = solved / dt

    # Single-puzzle latency on the hardest famous board (warm compile),
    # interleaved with the RPC-floor and amortized-chain measurements in ONE
    # loop (VERDICT r4 weak #2: separate loops let tunnel drift between them
    # exceed the quantity being resolved — BENCH_r04 recorded p50 < floor).
    # Each iteration samples floor (one trivial dispatch+fetch), then one
    # solve, then (first 3 iterations) a K-solve back-to-back chain; the
    # floor min and solve median now share every iteration's tunnel weather,
    # so floor <= p50 holds unless the tunnel shifts WITHIN an iteration.
    import jax.numpy as jnp

    lat_cfg = SolverConfig(min_lanes=256, stack_slots=64)
    one = np.asarray(HARD_9[0], dtype=np.int32)[None]
    r = solve_batch(one, SUDOKU_9, lat_cfg)
    int(np.asarray(r.steps))  # warm the solve path
    tiny = jnp.zeros(8, jnp.int32)
    _ = np.asarray(tiny + 1)  # warm the trivial dispatch
    k = 32
    times, floors = [], []
    chain_s = float("inf")
    for i in range(9):
        t0 = time.perf_counter()
        _ = np.asarray(tiny + 1)
        floors.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        r = solve_batch(one, SUDOKU_9, lat_cfg)
        int(np.asarray(r.steps))  # force the value round-trip
        times.append(time.perf_counter() - t0)
        if i < 3:
            # Device-only latency (VERDICT r3 #8): K solves dispatched
            # back-to-back (in-order device execution) cost
            # floor + K * T_device; subtract the floor and divide.
            t0 = time.perf_counter()
            for _ in range(k):
                r = solve_batch(one, SUDOKU_9, lat_cfg)
            int(np.asarray(r.steps))  # one sync drains the whole chain
            chain_s = min(chain_s, time.perf_counter() - t0)
    p50_ms = float(np.median(times)) * 1e3
    floor_s = min(floors)
    # Subtract a floor sampled in the SAME iterations the chains ran in
    # (floors[:3]): the 9-sample min may come from a different
    # tunnel-weather window, and /k only dilutes, not removes, that drift.
    device_ms = max(0.0, (chain_s - min(floors[:3])) / k) * 1e3

    out = {
        "metric": "hard9x9_bulk_boards_per_s_per_chip",
        "value": round(boards_per_s, 1),
        "unit": "boards/s",
        "vs_baseline": round(boards_per_s / BASELINE_BOARDS_PER_S, 1),
        "batch": b,
        "solved": solved,
        "searched": res.searched,
        "by_propagation": int(res.by_propagation.sum()),
        "wall_s": round(dt, 3),
        "p50_single_hard_ms": round(p50_ms, 2),
        "device_only_single_hard_ms": round(device_ms, 2),
        "rpc_floor_ms": round(floor_s * 1e3, 2),
        "device": str(jax.devices()[0].platform),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
