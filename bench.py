"""Headline benchmark: hard-9x9 throughput (boards solved/s) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference solves one easy 9x9 via `POST /solve` in 3.13 s on
this container (BASELINE.md, measured from /root/reference/DHT_Node.py live)
— an effective 0.3195 boards/s/node.  ``vs_baseline`` is our boards/s over
that figure, i.e. a direct end-to-end speedup multiple on the same workload
family (and our bench set is *harder*: 17-28 clue boards, not easy ones).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_BOARDS_PER_S = 1.0 / 3.13  # reference: easy 9x9 end-to-end (BASELINE.md)


def main() -> None:
    import os

    import jax

    from distributed_sudoku_solver_tpu.models.geometry import SUDOKU_9
    from distributed_sudoku_solver_tpu.ops.frontier import SolverConfig
    from distributed_sudoku_solver_tpu.ops.solve import solve_batch
    from distributed_sudoku_solver_tpu.utils.puzzles import HARD_9, puzzle_batch

    os.environ.setdefault(
        "DSST_PUZZLE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".cache", "puzzles"),
    )
    batch = 512
    gen = puzzle_batch(SUDOKU_9, batch - len(HARD_9), seed=7, n_clues=24)
    grids = np.concatenate([np.stack(HARD_9), gen]).astype(np.int32)

    cfg = SolverConfig(min_lanes=grids.shape[0], stack_slots=64)
    # Warm-up: compile + first run.
    res = solve_batch(grids, SUDOKU_9, cfg)
    jax.block_until_ready(res)

    n_iters = 5
    t0 = time.perf_counter()
    for _ in range(n_iters):
        res = solve_batch(grids, SUDOKU_9, cfg)
        jax.block_until_ready(res)
    dt = (time.perf_counter() - t0) / n_iters

    solved = int(np.asarray(res.solved).sum())
    boards_per_s = solved / dt
    out = {
        "metric": "hard9x9_boards_per_s_per_chip",
        "value": round(boards_per_s, 2),
        "unit": "boards/s",
        "vs_baseline": round(boards_per_s / BASELINE_BOARDS_PER_S, 1),
        "batch": grids.shape[0],
        "solved": solved,
        "wall_s_per_batch": round(dt, 4),
        "device": str(jax.devices()[0].platform),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
